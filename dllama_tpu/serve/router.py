"""Fleet router — health-driven HTTP dispatch over N api-server replicas.

The single-process scheduler (runtime/serving.py) is the scaling ceiling
for millions-of-users traffic: replica loss, draining, and overload must
become *fleet*-level concerns, not per-process ones (ROADMAP item 3;
"Distributed Inference Performance Optimization for LLMs on CPUs"
motivates the scheduler-over-engines topology). This module is that
tier, in the repo's idiom — stdlib http/sockets/threads only; no
model, no tokenizer, no device (the package import is the only jax the
process ever sees — a backend is never initialized). It fronts any
number of ``python -m dllama_tpu api`` replicas::

    python -m dllama_tpu router --replica http://10.0.0.1:9990 \\
        --replica http://10.0.0.2:9990 --port 8080

Pieces, and the failure contract each one carries (the PR2 failure
semantics re-proven one level up; tests/test_router.py drives every
path with chaos):

* **Health probes** — one daemon thread per replica polls the replica's
  existing ``GET /readyz`` (the machine-readable ``code`` field:
  draining / crashed / queue_full / loading) and ``GET /metrics``
  (queue depth, in-flight, block occupancy) on a jittered interval, so
  a fleet of routers never synchronizes its probe bursts.
* **Least-loaded dispatch with prefix-cache-aware session affinity** —
  a request's affinity key (body ``session_id``/``user``, else a hash
  of the conversation's first message — the prefix the replica-side
  NaiveCache / paged block sharing keys on) sticks to its replica
  while that replica stays healthy, so a returning session lands where
  its KV blocks live; everything else goes to the lowest
  queue+in-flight score.
* **Per-replica circuit breaker** — consecutive connect/5xx failures
  eject the replica (``dllama_router_ejects_total``); probes keep
  hitting it on bounded exponential backoff, and the first half-open
  success re-admits it (``dllama_router_readmits_total``).
* **Per-request budgets** — a dispatch that fails before the FIRST
  byte reaches the client is transparently retried once on a different
  replica (``dllama_router_retries_total``); when every replica is
  saturated (or the router-level ``--max-queue`` in-flight bound is
  hit) the request is shed with 429 + ``Retry-After``
  (``dllama_router_shed_total``).
* **Durable streams** — a stream that dies mid-flight (EOF without the
  ``[DONE]`` sentinel, a read error, or a replica-authored terminal
  ``finish_reason: "error"`` chunk from a crash/watchdog fail-all) is
  re-dispatched to a healthy replica as a token-exact spliced
  continuation when its chunks carried the batched replica's
  ``dllama`` index stamps: the router replays the full token history
  (body ``resume_from``/``resume_tokens`` + the
  ``X-Dllama-Resume-From`` header), prefers pulling the prefix KV from
  any advertising peer (the dying donor included) over the checksummed
  wire, and drops any replayed index so delivery is exactly-once
  (``dllama_router_stream_resumes_total{outcome}`` /
  ``dllama_router_stream_resume_ms``; ``rt_resume`` span). Bounded by
  ``--max-stream-resumes`` (default 1) and the remaining
  ``--request-timeout`` budget — past either bound, and for unstamped
  streams always, the legacy contract stands: an explicit terminal SSE
  error event naming the 502 plus ``[DONE]`` — never a silent hang.
* **Drain awareness** — a replica whose ``/readyz`` goes 503
  (draining) stops receiving new dispatches while its in-flight
  streams finish; the router's own SIGTERM does the same one level up
  (``/readyz`` flips, accepted work completes).
* **Fleet trace identity** — every completion dispatch carries an
  ``X-Dllama-Request-Id`` (client-supplied when sanitary, else minted
  here) plus an ``X-Dllama-Hop`` attempt index; the router keeps its
  own span ring (:class:`RouterSpanRing`, phases =
  telemetry.ROUTER_PHASES) so ``GET /debug/fleet/timeline`` — and the
  offline ``python -m dllama_tpu fleettrace`` joiner — can render one
  Chrome-trace flow per request across the router and every replica
  it touched (``runtime/flightrec.fleet_chrome_trace``).
* **SLO observatory** — ``--slo "ttft_p95_ms=500,itl_p50_ms=40,
  shed_rate=0.01"`` (or a JSON file) evaluates declarative objectives
  over router-measured streaming histograms with burn-rate windows
  (``runtime/slo``): ``GET /debug/slo``, the
  ``dllama_slo_compliance`` / ``dllama_slo_burn_rate`` gauges, and an
  SLO fragment on the ``--stats`` line.

Surfaces: ``/readyz`` (ready iff >= 1 dispatchable replica, same JSON
body contract as the replicas), ``/healthz``, ``/metrics``
(``dllama_router_*`` in the PR1 telemetry vocabulary, including the
router-measured TTFT/connect/retry latency histograms),
``/debug/fleet`` (per-replica breaker/load/probe state + the router
span ring), ``/debug/fleet/timeline``, ``/debug/slo``, and transparent
proxying of ``/v1/chat/completions`` + ``/v1/models``.

Thread model (machine-checked by dlint's thread-ownership rules): one
probe thread per replica owns that replica's health transitions; HTTP
handler threads dispatch/relay and only touch shared state under the
per-replica or router lock (``# dlint: guarded-by=...``).
"""

from __future__ import annotations

import hashlib
import http.client
import json
import random
import re
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit

from ..runtime import failpoints, flightrec, slo, telemetry, tenancy

# known routes for the HTTP request counter's route label (the router's
# twin of serve/api.py _ROUTES; anything else folds into "other")
_ROUTES = ("/v1/chat/completions", "/v1/models", "/metrics",
           "/health", "/healthz", "/readyz", "/debug/fleet",
           "/debug/fleet/timeline", "/debug/fleet/tenants",
           "/debug/slo")

# fleet trace identity headers — canonical parse side in serve/api.py
# (FLEET_RID_HEADER / FLEET_HOP_HEADER / FLEET_RID_RE there); spelled
# here too so this module's import graph stays engine-free. The id
# charset is closed because the value travels verbatim into response
# headers, dumps, and logs on every tier.
FLEET_RID_HEADER = "X-Dllama-Request-Id"
FLEET_HOP_HEADER = "X-Dllama-Hop"
# KV migration hint stamped on first-hop dispatches: "host:port" of a
# peer replica whose paged pool holds the prompt's prefix (the replica
# pulls it over the kvwire stream instead of recomputing). Re-spelled
# from serve/api.py for the same engine-free-import reason as above.
KV_PEER_HEADER = "X-Dllama-KV-Peer"
# Mid-stream failover: a spliced continuation names the count of tokens
# the client already holds; the replica admits the request with the full
# token history (body "resume_from"/"resume_tokens") and emits nothing
# at or below that index. Re-spelled from serve/api.py, same reason.
RESUME_FROM_HEADER = "X-Dllama-Resume-From"
# Tenant identity: sanitized at the edge (runtime/tenancy — absent or
# malformed collapses to "anon"), echoed on every router-authored
# answer, and forwarded on EVERY upstream dispatch — first hops, retry
# hops, spliced stream continuations, and prefill warm-ups alike — so a
# replica never misattributes router-originated work to "anon".
# Re-spelled from serve/api.py, same engine-free-import reason.
TENANT_HEADER = "X-Dllama-Tenant"
# Closed outcome vocabulary of dllama_router_stream_resumes_total (the
# failure-taxonomy dlint rule holds it to telemetry's label docs and
# PERF.md): resumed — continuation spliced, the client's transcript
# continued token-exactly; exhausted — another death after
# --max-stream-resumes continuations; no_budget — no remaining
# --request-timeout budget to resume into; failed — the re-dispatch
# itself found no healthy replica or died before the splice.
RESUME_OUTCOMES = ("resumed", "exhausted", "no_budget", "failed")
_RID_SAFE_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# upstream response headers relayed verbatim; everything hop-by-hop or
# regenerated by our own http.server (Date, Server) is dropped
_RELAY_HEADERS = ("Content-Type", "Content-Length", "Retry-After",
                  "Cache-Control")

# consecutive connect/5xx failures before the breaker ejects a replica
EJECT_AFTER = 3
# half-open probe backoff while ejected: bounded exponential
BACKOFF_MIN_S = 0.5
BACKOFF_MAX_S = 30.0

# the replica-state vocabulary /debug/fleet and the probes speak:
# loading (never successfully probed), up (dispatchable), unready
# (replica /readyz said no — its `code` says why), down (breaker-ejected)
STATES = ("loading", "up", "unready", "down")


def affinity_key(body: dict) -> str | None:
    """The session-stickiness key: an explicit ``session_id``/``user``
    field when the client sent one, else a hash of the conversation's
    FIRST message (role + content head) — the stable prefix of a
    multi-turn conversation, which is exactly what the replica-side
    NaiveCache / paged block sharing can reuse."""
    sid = body.get("session_id") or body.get("user")
    if isinstance(sid, str) and sid:
        return "sid:" + sid
    msgs = body.get("messages")
    if isinstance(msgs, list) and msgs and isinstance(msgs[0], dict):
        m = msgs[0]
        head = f"{m.get('role')}\x00{str(m.get('content'))[:256]}"
        return "pfx:" + hashlib.sha1(
            head.encode("utf-8", "replace")).hexdigest()
    return None


class Replica:
    """One upstream api-server: probe-observed health + load, the
    circuit breaker, and the router-side in-flight count.

    Ownership: the replica's probe thread drives state transitions from
    probe results; handler threads record dispatch outcomes and read
    dispatchability — every mutation of the shared fields holds
    ``_lock`` (dlint lock-guard enforces the declarations below)."""

    def __init__(self, url: str, *, eject_after: int = EJECT_AFTER,
                 backoff_min_s: float = BACKOFF_MIN_S,
                 backoff_max_s: float = BACKOFF_MAX_S,
                 connect_timeout_s: float = 2.0,
                 read_timeout_s: float = 120.0):
        u = urlsplit(url if "//" in url else f"http://{url}")
        if u.scheme not in ("", "http") or not u.hostname or not u.port:
            raise ValueError(f"replica URL must be http://host:port, "
                             f"got {url!r}")
        self.name = f"{u.hostname}:{u.port}"
        self.host, self.port = u.hostname, u.port
        self.eject_after = eject_after
        self.backoff_min_s = backoff_min_s
        self.backoff_max_s = backoff_max_s
        self.connect_timeout_s = connect_timeout_s
        self.read_timeout_s = read_timeout_s
        self._lock = threading.Lock()
        self.state = "loading"         # dlint: guarded-by=_lock
        self.unready_code = "loading"  # dlint: guarded-by=_lock
        self.queue_depth = 0.0         # dlint: guarded-by=_lock
        self.engine_inflight = 0.0     # dlint: guarded-by=_lock
        self.block_occupancy = 0.0     # dlint: guarded-by=_lock
        self.inflight = 0              # dlint: guarded-by=_lock
        self.consecutive_failures = 0  # dlint: guarded-by=_lock
        self.ejected_until = 0.0       # dlint: guarded-by=_lock
        self.backoff_s = backoff_min_s  # dlint: guarded-by=_lock
        self.last_probe_t = 0.0        # dlint: guarded-by=_lock
        # disaggregation/migration advertisement off the last /readyz
        # body: the replica's --role tag and its resident-prefix keys
        self.role = None               # dlint: guarded-by=_lock
        self.kv_prefixes: list = []    # dlint: guarded-by=_lock
        # invoked OUTSIDE the lock when the breaker ejects this replica
        # (the FleetRouter hangs its sticky-affinity purge here)
        self.on_eject = None
        reg = telemetry.registry()
        self._g_up = reg.gauge(telemetry.ROUTER_REPLICA_UP)
        self._g_inflight = reg.gauge(telemetry.ROUTER_INFLIGHT)
        self._c_ejects = reg.counter(telemetry.ROUTER_EJECTS)
        self._c_readmits = reg.counter(telemetry.ROUTER_READMITS)
        self._g_up.set(0, replica=self.name)
        self._g_inflight.set(0, replica=self.name)

    # -- dispatch-side reads/writes (handler threads) ------------------------

    def dispatchable(self) -> bool:  # dlint: owner=any
        with self._lock:
            return self.state == "up"

    def load_score(self) -> float:  # dlint: owner=any
        """Least-loaded ranking: the replica's own reported queue +
        in-flight plus the router-side in-flight count (which covers
        dispatches newer than the last probe)."""
        with self._lock:
            return self.queue_depth + self.engine_inflight + self.inflight

    def begin_request(self) -> None:  # dlint: owner=any
        with self._lock:
            self.inflight += 1
            n = self.inflight
        self._g_inflight.set(n, replica=self.name)

    def end_request(self) -> None:  # dlint: owner=any
        with self._lock:
            self.inflight -= 1
            n = self.inflight
        self._g_inflight.set(n, replica=self.name)

    def note_unready(self, code: str) -> None:  # dlint: owner=any
        """An explicit unready answer observed on the DISPATCH path (a
        503 whose body code says draining/queue_full): classify the
        replica the way the probe would — alive but not dispatchable —
        WITHOUT feeding the breaker. A draining pod must never be
        ejected into the crash-backoff schedule."""
        with self._lock:
            if self.state != "down":
                self.state = "unready"
                self.unready_code = code
            self.consecutive_failures = 0
        self._g_up.set(0, replica=self.name)

    def note_failure(self) -> None:  # dlint: owner=any
        """One connect/5xx failure toward the breaker threshold; at
        ``eject_after`` consecutive ones the replica is ejected and the
        half-open backoff schedule starts."""
        ejected = False
        with self._lock:
            self.consecutive_failures += 1
            if self.state != "down" \
                    and self.consecutive_failures >= self.eject_after:
                self.state = "down"
                self.unready_code = "crashed"
                self.backoff_s = self.backoff_min_s
                self.ejected_until = time.monotonic() + self.backoff_s
                ejected = True
        if ejected:
            self._g_up.set(0, replica=self.name)
            self._c_ejects.inc(replica=self.name)
            if self.on_eject is not None:
                self.on_eject(self)

    def is_prefill(self) -> bool:  # dlint: owner=any
        with self._lock:
            return self.role == "prefill"

    def holds_prefix(self, key: str) -> bool:  # dlint: owner=any
        """Whether this replica's last probe advertised ``key`` as a
        resident paged-KV prefix. Advisory by construction: the pool
        evicts independently of the probe cadence, so a stale True costs
        one export round trip that answers \"not resident\"."""
        with self._lock:
            return self.state != "down" and key in self.kv_prefixes

    def purge_kv_prefixes(self) -> None:  # dlint: owner=any
        """Breaker-eject hygiene: a down replica must stop being a
        KV-donor candidate NOW, not one stale ``holds_prefix`` miss per
        dispatch until its next probe refresh (``holds_prefix`` already
        refuses ``down`` replicas — this keeps /debug/fleet and any
        direct reader honest too)."""
        with self._lock:
            self.kv_prefixes = []

    def note_success(self, *, from_probe: bool = False) -> None:  # dlint: owner=any
        """A successful probe or dispatch: failures reset; an ejected
        replica is re-admitted (the half-open probe succeeded). Only a
        PROBE may promote a probe-classified ``unready`` replica back
        to ``up`` — a late response arriving after the replica started
        draining must not pull new sessions onto it; dispatches promote
        only from ``loading``/``down``."""
        with self._lock:
            self.consecutive_failures = 0
            promote = from_probe or self.state in ("loading", "down")
            readmitted = promote and self.state == "down"
            if promote and self.state != "up":
                self.state = "up"
                self.unready_code = "ok"
                self.backoff_s = self.backoff_min_s
            is_up = self.state == "up"
        if is_up:
            self._g_up.set(1, replica=self.name)
        if readmitted:
            self._c_readmits.inc(replica=self.name)

    # -- probe side (this replica's probe thread) ----------------------------

    def probe_due(self, interval_s: float) -> float:  # dlint: owner=probe-thread
        """Seconds until the next probe: the jittered interval while
        healthy, the breaker's current backoff while ejected (the
        half-open schedule)."""
        with self._lock:
            if self.state == "down":
                return max(0.0, self.ejected_until - time.monotonic())
        return interval_s * random.uniform(0.8, 1.2)

    def probe_once(self) -> None:  # dlint: owner=probe-thread
        """One /readyz + /metrics round trip; classifies the replica and
        refreshes its load snapshot. Runs on this replica's probe thread
        only — the transitions ride the same breaker accounting the
        dispatch path uses."""
        with self._lock:
            self.last_probe_t = time.monotonic()
        try:
            status, body = self._get("/readyz")
        except OSError:
            half_open_failed = False
            with self._lock:
                if self.state == "down":
                    # half-open probe failed: double the backoff, bounded
                    self.backoff_s = min(self.backoff_s * 2,
                                         self.backoff_max_s)
                    self.ejected_until = time.monotonic() + self.backoff_s
                    half_open_failed = True
            if not half_open_failed:
                self.note_failure()
            return
        # the disaggregation/migration advertisement rides the same body
        # on BOTH answers (a draining replica still holds its blocks);
        # the vocabulary is closed — role outside {prefill, decode} and
        # non-string prefixes are dropped, never stored
        role, prefixes = None, []
        try:
            rz = json.loads(body)
            if rz.get("role") in ("prefill", "decode"):
                role = rz["role"]
            pf = rz.get("kv_prefixes")
            if isinstance(pf, list):
                prefixes = [p for p in pf if isinstance(p, str)][:64]
        except (ValueError, AttributeError):
            pass
        with self._lock:
            self.role = role
            self.kv_prefixes = prefixes
        if status == 200:
            self.note_success(from_probe=True)
        else:
            # READY_CODES is the closed vocabulary (serve/api.py); an
            # unknown/missing code degrades to "crashed" rather than
            # leaking arbitrary strings into /debug/fleet and dispatch
            from .api import READY_CODES

            code = "crashed"
            try:
                got = json.loads(body).get("code")
                if got in READY_CODES:
                    code = got
            except (ValueError, AttributeError):
                pass
            with self._lock:
                # an explicit unready answer is drain/overload signal,
                # not a breaker failure: the replica is alive and will
                # come back on its own (draining pods must not be
                # ejected into a backoff schedule) — and an ejected
                # replica that ANSWERS again is connect-level alive, so
                # it leaves "down" for "unready" rather than busy-
                # probing a zeroed backoff
                self.state = "unready"
                self.unready_code = code
                self.consecutive_failures = 0
            self._g_up.set(0, replica=self.name)
        try:
            _, mtext = self._get("/metrics")
            load = _parse_replica_metrics(mtext)
            with self._lock:
                self.queue_depth = load.get("queue_depth", 0.0)
                self.engine_inflight = load.get("inflight", 0.0)
                self.block_occupancy = load.get("block_occupancy", 0.0)
        except OSError:
            pass  # readyz already classified health; stale load is fine

    def _get(self, path: str) -> tuple[int, str]:  # dlint: owner=probe-thread
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.connect_timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read().decode("utf-8", "replace")
        finally:
            conn.close()

    def snapshot(self) -> dict:  # dlint: owner=any
        with self._lock:
            return {
                "replica": self.name,
                "state": self.state,
                "code": self.unready_code if self.state != "up" else "ok",
                "queue_depth": self.queue_depth,
                "engine_inflight": self.engine_inflight,
                "block_occupancy": self.block_occupancy,
                "router_inflight": self.inflight,
                "role": self.role,
                "kv_prefixes": list(self.kv_prefixes),
                "consecutive_failures": self.consecutive_failures,
                "backoff_s": self.backoff_s if self.state == "down" else 0.0,
                "last_probe_s_ago": (round(time.monotonic()
                                           - self.last_probe_t, 3)
                                     if self.last_probe_t else None),
            }


def _parse_replica_metrics(text: str) -> dict:
    """Pull the load gauges the dispatcher ranks on out of a replica's
    Prometheus text exposition (no client library — repo idiom)."""
    want = {"dllama_queue_depth": "queue_depth",
            "dllama_requests_in_flight": "inflight",
            "dllama_kv_blocks_used": "_blocks_used",
            "dllama_kv_blocks_total": "_blocks_total"}
    out: dict = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        name, _, rest = line.partition(" ")
        name = name.partition("{")[0]
        key = want.get(name)
        if key is None:
            continue
        try:
            out[key] = float(rest.strip())
        except ValueError:
            continue
    total = out.pop("_blocks_total", 0.0)
    used = out.pop("_blocks_used", 0.0)
    if total:
        out["block_occupancy"] = used / total
    return out


class RouterSpanRing:
    """Bounded ring of router-side request spans — the fleet tier's
    twin of ``telemetry.SpanTracer``. Records carry the STRING fleet
    request id (the ``X-Dllama-Request-Id`` value) plus dispatch
    context (``replica``, ``hop``); phases come from
    ``telemetry.ROUTER_PHASES`` and are closed-world-checked by the
    span-phases dlint rule exactly like the engine span vocabulary.
    Served raw as ``/debug/fleet``'s ``spans`` key — which is also the
    offline joiner's ``--router-dump`` input — and joined with replica
    flight dumps by ``flightrec.fleet_chrome_trace``."""

    RING = 2048

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.RING)  # dlint: guarded-by=_lock

    def emit_span(self, request_id: str, phase: str, start_ns: int,
                  end_ns: int, *, replica: str = "", hop: int = -1,
                  **extra) -> None:  # dlint: owner=any
        """One completed router-side span; ``start_ns == end_ns`` marks
        an instant event (dispatch decisions, eject markers)."""
        rec = {"request_id": str(request_id), "phase": phase,
               "start_ns": int(start_ns), "end_ns": int(end_ns)}
        if replica:
            rec["replica"] = replica
        if hop >= 0:
            rec["hop"] = hop
        rec.update(extra)
        with self._lock:
            self._ring.append(rec)

    def raw_spans(self) -> list[dict]:  # dlint: owner=any
        with self._lock:
            return [dict(s) for s in self._ring]


class FleetRouter:
    """Replica set + probe threads + dispatch policy — the state behind
    :func:`make_router_handler`."""

    AFFINITY_MAX = 4096  # bounded sticky map (LRU)

    def __init__(self, replica_urls: list[str], *,
                 probe_interval_s: float = 2.0, max_inflight: int = 0,
                 eject_after: int = EJECT_AFTER,
                 backoff_min_s: float = BACKOFF_MIN_S,
                 backoff_max_s: float = BACKOFF_MAX_S,
                 connect_timeout_s: float = 2.0,
                 read_timeout_s: float = 120.0,
                 max_stream_resumes: int = 1,
                 request_timeout_s: float = 0.0,
                 start_probes: bool = True,
                 slo_objectives: dict[str, float] | None = None):
        if not replica_urls:
            raise ValueError("at least one --replica URL is required")
        self.replicas = [Replica(u, eject_after=eject_after,
                                 backoff_min_s=backoff_min_s,
                                 backoff_max_s=backoff_max_s,
                                 connect_timeout_s=connect_timeout_s,
                                 read_timeout_s=read_timeout_s)
                         for u in replica_urls]
        if len({r.name for r in self.replicas}) != len(self.replicas):
            raise ValueError("duplicate --replica URLs")
        for r in self.replicas:
            # affinity hygiene: a breaker eject drops the replica's
            # sticky entries immediately (not one dispatchable() miss
            # per returning session at a time)
            r.on_eject = self._on_replica_eject
        self.probe_interval_s = probe_interval_s
        self.max_inflight = max_inflight
        self.read_timeout_s = read_timeout_s
        # mid-stream failover budget: how many spliced continuations one
        # stream may consume (--max-stream-resumes; the N+1th death is
        # terminal) and the wall deadline resumes must fit inside
        # (--request-timeout; 0 = unbounded — a client body "timeout"
        # still bounds its own request)
        self.max_stream_resumes = max_stream_resumes
        self.request_timeout_s = request_timeout_s
        self._lock = threading.Lock()
        self._affinity: OrderedDict = OrderedDict()  # dlint: guarded-by=_lock
        self._inflight_total = 0                     # dlint: guarded-by=_lock
        self._draining = False                       # dlint: guarded-by=_lock
        self._rid_seq = 0                            # dlint: guarded-by=_lock
        # boot-unique prefix: two router incarnations never mint the
        # same id, so joined dumps across a restart stay unambiguous
        self._rid_boot = f"{random.getrandbits(32):08x}"
        self._stop = threading.Event()
        self.spans = RouterSpanRing()
        self.slo = (slo.SloEngine(slo_objectives)
                    if slo_objectives else None)
        reg = telemetry.registry()
        self.c_dispatch = reg.counter(telemetry.ROUTER_DISPATCHES)
        self.c_retries = reg.counter(telemetry.ROUTER_RETRIES)
        self.c_shed = reg.counter(telemetry.ROUTER_SHED)
        self.c_affinity = reg.counter(telemetry.ROUTER_AFFINITY_HITS)
        self.c_affinity_purged = reg.counter(
            telemetry.ROUTER_AFFINITY_PURGED)
        self.c_retry_hops = reg.counter(telemetry.ROUTER_RETRY_HOPS)
        self.h_ttft = reg.histogram(telemetry.ROUTER_TTFT_MS)
        self.h_connect = reg.histogram(telemetry.ROUTER_CONNECT_MS)
        self.h_retry = reg.histogram(telemetry.ROUTER_RETRY_MS)
        self.c_resumes = reg.counter(telemetry.ROUTER_STREAM_RESUMES)
        self.h_resume = reg.histogram(telemetry.ROUTER_STREAM_RESUME_MS)
        self._threads: list[threading.Thread] = []
        if start_probes:
            self.start()

    def mint_rid(self, client_rid: str | None) -> str:  # dlint: owner=any
        """The fleet request id for one completion: a client-supplied
        ``X-Dllama-Request-Id`` is honored when it matches the sanitary
        charset (``[A-Za-z0-9._-]{1,64}`` — the value travels verbatim
        into headers, dumps, and logs on every tier), anything else is
        replaced by a freshly minted boot-unique id."""
        if isinstance(client_rid, str) and _RID_SAFE_RE.match(client_rid):
            return client_rid
        with self._lock:
            self._rid_seq += 1
            n = self._rid_seq
        return f"r{self._rid_boot}-{n:x}"

    def start(self) -> None:  # dlint: owner=any
        for rep in self.replicas:
            t = threading.Thread(target=self._probe_loop, args=(rep,),
                                 daemon=True,
                                 name=f"dllama-probe-{rep.name}")
            t.start()
            self._threads.append(t)

    def close(self) -> None:  # dlint: owner=any
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    def begin_drain(self) -> None:  # dlint: owner=any
        """Flip the router's own /readyz (a load balancer above stops
        routing) while accepted work keeps relaying — the same two-phase
        drain the replicas implement."""
        with self._lock:
            self._draining = True

    def is_draining(self) -> bool:  # dlint: owner=any
        with self._lock:
            return self._draining

    def _probe_loop(self, rep: Replica) -> None:  # dlint: owner=probe-thread
        # first probe immediately (a router must converge fast at start),
        # then the jittered interval / half-open backoff schedule
        while not self._stop.is_set():
            rep.probe_once()
            if self._stop.wait(rep.probe_due(self.probe_interval_s)):
                return

    # -- dispatch policy -----------------------------------------------------

    def _on_replica_eject(self, rep: Replica) -> None:  # dlint: owner=any
        """Breaker eject → sticky-map hygiene: purge every affinity
        entry pointing at the ejected replica so returning sessions
        re-pick (and possibly KV-migrate) immediately instead of riding
        a dead pointer through a dispatchable() miss each."""
        rep.purge_kv_prefixes()
        with self._lock:
            stale = [k for k, v in self._affinity.items() if v is rep]
            for k in stale:
                del self._affinity[k]
        if stale:
            self.c_affinity_purged.inc(len(stale), replica=rep.name)

    def prefill_replicas(self) -> list:  # dlint: owner=any
        """Dispatchable prefill-role replicas (disaggregation donors)."""
        return [r for r in self.replicas
                if r.dispatchable() and r.is_prefill()]

    def kv_donor(self, key: str | None,
                 chosen: Replica) -> Replica | None:  # dlint: owner=any
        """The migration source for a fleet-global prefix hit: a replica
        (≠ ``chosen``) whose last probe advertised ``key`` as resident.
        Advisory — a stale advertisement costs one export probe that
        answers \"not resident\", after which the destination recomputes."""
        if key is None:
            return None
        for rep in self.replicas:
            if rep is not chosen and rep.holds_prefix(key):
                return rep
        return None

    def pick(self, key: str | None,
             exclude: set | None = None) -> Replica | None:  # dlint: owner=any
        """The dispatch decision: sticky replica while it stays healthy
        (and isn't excluded by a retry), else least-loaded; updates the
        sticky map so the session returns here next time. Prefill-role
        replicas serve warm-up work only, so they are excluded — unless
        they are ALL that remains, in which case availability beats
        disaggregation purity."""
        exclude = exclude or set()
        if key is not None:
            with self._lock:
                sticky = self._affinity.get(key)
                if sticky is not None:
                    self._affinity.move_to_end(key)
            if sticky is not None and sticky not in exclude \
                    and sticky.dispatchable() and not sticky.is_prefill():
                self.c_affinity.inc()
                return sticky
        live = [r for r in self.replicas
                if r not in exclude and r.dispatchable()]
        if not live:
            return None
        decode = [r for r in live if not r.is_prefill()]
        chosen = min(decode or live, key=lambda r: r.load_score())
        if key is not None:
            with self._lock:
                self._affinity[key] = chosen
                self._affinity.move_to_end(key)
                while len(self._affinity) > self.AFFINITY_MAX:
                    self._affinity.popitem(last=False)
        return chosen

    def unready_reason(self) -> tuple[str, str]:  # dlint: owner=any
        """(human reason, machine code) when no replica is dispatchable
        — the router-level /readyz body and the no-replica error path
        share this one classification."""
        with self._lock:
            if self._draining:
                return "router is draining", "draining"
        snaps = [r.snapshot() for r in self.replicas]
        codes = {s["code"] for s in snaps}
        if codes <= {"loading"}:
            return "no replica probed ready yet", "loading"
        if codes <= {"queue_full", "draining", "loading"} \
                and "queue_full" in codes:
            return "every replica is saturated (queue_full)", "queue_full"
        if codes <= {"draining", "loading"}:
            return "every replica is draining", "draining"
        return "no healthy replica (all ejected or unready)", "crashed"

    def readiness(self) -> tuple[bool, str, str]:  # dlint: owner=any
        with self._lock:
            if self._draining:
                return False, "router is draining", "draining"
        if any(r.dispatchable() for r in self.replicas):
            return True, "ok", "ok"
        reason, code = self.unready_reason()
        return False, reason, code

    def admit(self) -> bool:  # dlint: owner=any
        """Router-level in-flight bound (--max-queue): False = shed."""
        with self._lock:
            if self._draining:
                return False
            if self.max_inflight and \
                    self._inflight_total >= self.max_inflight:
                return False
            self._inflight_total += 1
        return True

    def release(self) -> None:  # dlint: owner=any
        with self._lock:
            self._inflight_total -= 1

    def fleet_snapshot(self) -> dict:  # dlint: owner=any
        with self._lock:
            n_aff = len(self._affinity)
            inflight = self._inflight_total
            draining = self._draining
        return {"replicas": [r.snapshot() for r in self.replicas],
                "inflight_total": inflight,
                "max_inflight": self.max_inflight,
                "affinity_entries": n_aff,
                "draining": draining,
                "probe_interval_s": self.probe_interval_s,
                # the router span ring rides the fleet snapshot: a saved
                # /debug/fleet body IS the fleettrace --router-dump file
                "spans": self.spans.raw_spans()}


class _UpstreamDied(Exception):
    """The replica connection failed or returned 5xx before the client
    saw a byte — the retryable class."""

    def __init__(self, msg: str, status: int | None = None,
                 headers=None, body: bytes = b"", code: str | None = None):
        super().__init__(msg)
        self.status = status  # a relayable 5xx when retry is impossible
        self.headers = headers
        self.body = body
        # the 5xx body's machine code when it carried one: draining /
        # queue_full answers classify the replica as unready, they do
        # NOT feed the circuit breaker
        self.code = code


class _StreamState:
    """Per-request resume ledger carried across relay attempts: every
    SSE event the client was sent passes through :meth:`admit`, which
    reads the replica's ``dllama`` stamp (``{"index": n, "tokens":
    [...]}``; serve/api.py batched mode) and keeps the transcript's
    position — ``n_tokens`` tokens held by the client, their ids in
    ``tokens``. A spliced continuation re-enters the same ledger, so a
    replayed index (``<= n_tokens``) is dropped before the client can
    see a duplicate: the exactly-once half of the token-exact contract
    (the gap-free half is the replica resuming AT ``n_tokens``)."""

    def __init__(self):
        self.headers_sent = False   # response status/headers relayed once
        self.stamped = False        # any dllama index stamp observed
        self.echo_relayed = False   # the index-0 prompt-echo chunk sent
        self.done = False           # the [DONE] sentinel reached the client
        self.upstream_error = False  # held-back terminal "error" chunk
        self.n_tokens = 0           # last stamped index relayed
        self.tokens: list[int] = []  # the ids behind those indices
        self.resumes = 0            # spliced continuations consumed
        # resume-latency attribution, armed by the resume dispatch and
        # consumed by the relay loop at the first continued event:
        # (t_detect_ns, t_redispatch_ns, t_connect_ns, resume_from)
        self.resume_t: tuple | None = None

    def resumable(self) -> bool:
        """Only a stamped stream whose ledger is self-consistent (ids
        held == indices relayed — what the replica-side resume admission
        validates) can be spliced; anything else keeps the legacy
        terminal-502 contract."""
        return self.stamped and len(self.tokens) == self.n_tokens

    def admit(self, evt: bytes) -> bool:
        """Whether one complete SSE event reaches the client; updates
        the ledger from the event's ``dllama`` stamp. Unstamped events
        (errors, usage epilogues, non-JSON) always pass."""
        body = evt.strip()
        if not body.startswith(b"data:"):
            return True
        data = body[5:].strip()
        if data == b"[DONE]":
            self.done = True
            return True
        try:
            obj = json.loads(data)
        except ValueError:
            return True
        if not isinstance(obj, dict):
            return True
        if self.stamped:
            # a replica-authored terminal `finish_reason: "error"` chunk
            # (scheduler crash fail-all, watchdog trip) is a mid-stream
            # death in a cleanly-FINed socket: hold it back and let the
            # caller splice a continuation — a terminal abort past the
            # resume budget still ends the stream explicitly
            ch = obj.get("choices")
            if isinstance(ch, list) and ch and isinstance(ch[0], dict) \
                    and ch[0].get("finish_reason") == "error":
                self.upstream_error = True
                return False
        meta = obj.get("dllama")
        if not isinstance(meta, dict):
            return True
        try:
            idx = int(meta.get("index"))
            toks = [int(t) for t in meta.get("tokens") or ()]
        except (TypeError, ValueError):
            return True
        self.stamped = True
        if idx == 0:
            # the prompt-echo chunk: once, ever (a from-zero re-dispatch
            # replays it; the client already holds it)
            if self.echo_relayed:
                return False
            self.echo_relayed = True
            return True
        if idx <= self.n_tokens:
            # a tail flush (same index, no new tokens) is text the
            # stop-string detector held back past the last counted
            # token — never yet relayed, so it passes; anything
            # carrying token ids at a held index is a splice replay
            return idx == self.n_tokens and not toks
        self.n_tokens = idx
        self.tokens.extend(toks)
        return True


class _StreamDied(Exception):
    """The upstream died AFTER the client saw stream bytes — not
    retryable as a fresh dispatch (the transcript is half-delivered);
    resumable as a spliced continuation when the chunks carried the
    replica's ``dllama`` index stamps. Carries the request's
    :class:`_StreamState` ledger and the underlying failure."""

    def __init__(self, st: _StreamState, exc: Exception):
        super().__init__(f"{type(exc).__name__}: {exc}")
        self.st = st
        self.exc = exc


def make_router_handler(fleet: FleetRouter):
    from .api import backpressure_headers

    class RouterHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        timeout = 120  # stalled-peer guard, same rationale as serve/api.py

        # per-request trace state (reset at the top of each do_GET/do_POST
        # — keep-alive reuses the handler instance across requests)
        _fleet_rid: str | None = None
        _tenant: str | None = None
        _t_first_ns: int | None = None

        def log_message(self, fmt, *args):
            print(f"🕸️ router {self.address_string()} {fmt % args}")

        def _count(self, status: int | str) -> None:
            path = self.path.split("?", 1)[0]
            route = path if path in _ROUTES else "other"
            telemetry.registry().counter(telemetry.HTTP_REQUESTS).inc(
                route=route, status=str(status))

        def _json(self, code: int, payload: dict,
                  headers: dict | None = None) -> None:
            self._count(code)
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if self._fleet_rid:
                # every router-authored answer names the request: the
                # client learns the minted id even on shed/error paths
                self.send_header(FLEET_RID_HEADER, self._fleet_rid)
            if self._tenant is not None:
                self.send_header(TENANT_HEADER, self._tenant)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        # -- upstream plumbing ----------------------------------------------

        def _open_upstream(self, rep: Replica, method: str, path: str,
                           body: bytes | None, extra_headers=None):
            """One upstream request; returns (conn, resp) with headers
            parsed. Raises :class:`_UpstreamDied` on connect failure or
            a 5xx answer (the breaker is fed by the caller).
            ``extra_headers`` carries the fleet trace identity
            (request-id + hop index) on completion dispatches."""
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=fleet.read_timeout_s)
            try:
                # the chaos sever point: an armed `proxy` failpoint
                # (conn_reset/broken_pipe/raise) kills this dispatch
                # exactly where a dying replica would
                failpoints.fire("proxy")
                headers = {}
                if body is not None:
                    headers["Content-Type"] = "application/json"
                if extra_headers:
                    headers.update(extra_headers)
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException,
                    failpoints.FailpointError) as e:
                conn.close()
                raise _UpstreamDied(
                    f"replica {rep.name} connection failed: "
                    f"{type(e).__name__}: {e}") from e
            if resp.status >= 500:
                # a 5xx before any relay is retryable on another
                # replica; keep the payload so a retry-exhausted path
                # can still pass it through unmangled, and its machine
                # code so a draining replica isn't breaker-ejected
                data = resp.read()
                hdrs = resp.getheaders()
                conn.close()
                code = None
                try:
                    code = json.loads(data).get("code")
                except (ValueError, AttributeError):
                    pass
                raise _UpstreamDied(
                    f"replica {rep.name} answered {resp.status}",
                    status=resp.status, headers=hdrs, body=data,
                    code=code)
            return conn, resp

        def _relay_headers(self, resp, status: int,
                           force_close: bool) -> None:
            self.send_response(status)
            for k, v in resp.getheaders():
                if k in _RELAY_HEADERS and k != FLEET_RID_HEADER:
                    self.send_header(k, v)
            if self._fleet_rid:
                # the fleet trace id rides every relayed response, so a
                # client can join its request into /debug/fleet/timeline
                self.send_header(FLEET_RID_HEADER, self._fleet_rid)
            if self._tenant is not None:
                self.send_header(TENANT_HEADER, self._tenant)
            if force_close:
                self.send_header("Connection", "close")
                self.close_connection = True
            self.end_headers()

        def _note_first_byte(self, rid: str, rep: Replica, hop: int,
                             t0_ns: int) -> None:
            """First upstream body byte relayed: the router-measured
            TTFT — ``rt_first_byte`` span (admission → now), the
            dllama_router_ttft_ms histogram, and the SLO observation —
            recorded once per request, whichever hop serves it."""
            if self._t_first_ns is not None or not rid:
                return
            now = telemetry.now_ns()
            self._t_first_ns = now
            ms = (now - t0_ns) / 1e6
            fleet.h_ttft.record(ms)
            fleet.spans.emit_span(rid, "rt_first_byte", t0_ns, now,
                                  replica=rep.name, hop=hop)
            if fleet.slo is not None:
                fleet.slo.observe_ttft(ms, tenant=self._tenant)

        def _end_stream(self, rid: str, rep: Replica, hop: int,
                        status) -> None:
            """Close the ``rt_stream`` span (first relayed byte → last)
            once the relay is over — clean end or mid-stream 502."""
            if self._t_first_ns is None or not rid:
                return
            fleet.spans.emit_span(rid, "rt_stream", self._t_first_ns,
                                  telemetry.now_ns(), replica=rep.name,
                                  hop=hop, code=str(status))

        def _relay_response(self, rep: Replica, conn, resp, *,
                            rid: str = "", hop: int = 0,
                            t0_ns: int = 0,
                            st: _StreamState | None = None) -> int:
            """Stream the upstream response to the client. Buffered when
            a Content-Length is known (an upstream death mid-body stays
            retryable because nothing reached the client); incremental
            for SSE/EOF-delimited bodies, event-parsed through the
            request's :class:`_StreamState` ledger so a mid-stream death
            raises :class:`_StreamDied` for the caller to either splice
            a continuation (``_resume_stream``) or send the explicit
            terminal 502 event. ``rid``/``hop``/``t0_ns`` feed the trace
            spans and the router-measured TTFT/ITL."""
            try:
                length = resp.getheader("Content-Length")
                if length is not None:
                    try:
                        data = resp.read(int(length))
                    except (OSError, http.client.HTTPException) as e:
                        raise _UpstreamDied(
                            f"replica {rep.name} died mid-body: "
                            f"{type(e).__name__}") from e
                    if len(data) < int(length):
                        raise _UpstreamDied(
                            f"replica {rep.name} died mid-body")
                    self._note_first_byte(rid, rep, hop, t0_ns)
                    self._relay_headers(resp, resp.status,
                                        force_close=False)
                    self.wfile.write(data)
                    self._end_stream(rid, rep, hop, resp.status)
                    return resp.status
                # EOF-delimited (the api server's SSE streams): relay as
                # data arrives; from the first byte on, failures are no
                # longer retryable as a fresh dispatch — a death raises
                # _StreamDied and the caller splices a continuation (a
                # stamped stream) or sends the terminal 502 event.
                # A dying replica's socket closes with a clean FIN, so
                # EOF alone can't prove completion: the api server's SSE
                # contract is that a healthy stream ends with the
                # ``data: [DONE]`` sentinel, and an EOF without it IS a
                # mid-stream death.
                is_sse = (resp.getheader("Content-Type") or "").startswith(
                    "text/event-stream")
                if st is None:
                    st = _StreamState()
                if not st.headers_sent:
                    self._relay_headers(resp, resp.status,
                                        force_close=True)
                    st.headers_sent = True
                buf = b""
                t_prev: int | None = None
                while True:
                    try:
                        chunk = resp.read1(65536)
                    except (OSError, http.client.HTTPException) as e:
                        raise _StreamDied(st, e) from e
                    if not chunk:
                        if is_sse and not st.done:
                            raise _StreamDied(st, ConnectionError(
                                "EOF before the [DONE] sentinel"))
                        self._end_stream(rid, rep, hop, resp.status)
                        return resp.status
                    now = telemetry.now_ns()
                    if t_prev is None:
                        self._note_first_byte(rid, rep, hop, t0_ns)
                    elif fleet.slo is not None:
                        # router-measured ITL: inter-chunk relay gaps
                        # (one SSE event per chunk in practice)
                        fleet.slo.observe_itl((now - t_prev) / 1e6,
                                              tenant=self._tenant)
                    t_prev = now
                    if not is_sse:
                        self.wfile.write(chunk)
                        self.wfile.flush()
                        continue
                    # event-parsed relay: the exactly-once filter needs
                    # whole `data:` events (split on the SSE separator),
                    # and in practice each chunk IS one event
                    buf += chunk
                    out = b""
                    while b"\n\n" in buf:
                        evt, buf = buf.split(b"\n\n", 1)
                        if st.upstream_error:
                            # the held-back terminal error chunk ends
                            # this upstream: its trailing [DONE] belongs
                            # to the dead stream, never to the client
                            break
                        if st.admit(evt):
                            out += evt + b"\n\n"
                    if out:
                        if st.resume_t is not None:
                            self._note_resume_spliced(rid, rep, hop,
                                                      st, now)
                        self.wfile.write(out)
                        self.wfile.flush()
                    if st.upstream_error:
                        raise _StreamDied(st, ConnectionError(
                            "upstream terminal error chunk"))
            finally:
                conn.close()

        def _note_resume_spliced(self, rid: str, rep: Replica, hop: int,
                                 st: _StreamState, now_ns: int) -> None:
            """First continued event of a spliced continuation reached
            the client: the resume succeeded — record the detect→
            first-token latency (dllama_router_stream_resume_ms), the
            outcome counter, and the ``rt_resume`` span with its phase
            attribution (re-dispatch decision, upstream connect, first
            continued token) in the span's extra fields."""
            t_detect, t_redispatch, t_connect, n_resume = st.resume_t
            st.resume_t = None
            fleet.c_resumes.inc(outcome="resumed")
            fleet.h_resume.record((now_ns - t_detect) / 1e6)
            fleet.spans.emit_span(
                rid, "rt_resume", t_detect, now_ns,
                replica=rep.name, hop=hop, resume_from=n_resume,
                detect_ms=round((t_redispatch - t_detect) / 1e6, 3),
                redispatch_ms=round((t_connect - t_redispatch) / 1e6, 3),
                first_token_ms=round((now_ns - t_connect) / 1e6, 3))

        def _stream_abort(self, rep: Replica, exc: Exception) -> None:
            """Mid-stream upstream death: an explicit terminal SSE event
            naming the 502, then [DONE] — the client can always tell a
            server-side abort from a dropped socket."""
            try:
                evt = {"error": {
                    "message": f"replica {rep.name} died mid-stream "
                               f"({type(exc).__name__})",
                    "type": "upstream_error", "code": 502}}
                self.wfile.write(b"data: "
                                 + json.dumps(evt).encode("utf-8")
                                 + b"\n\n")
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except OSError:
                pass  # the peer is gone too; nothing left to tell it
            self.close_connection = True

        def _resume_stream(self, body: dict, rid: str, rep: Replica,
                           hop: int, sd: _StreamDied,
                           t0_ns: int) -> int:
            """Mid-stream failover: the serving replica died with the
            transcript half-delivered — re-dispatch the request to a
            healthy replica as a spliced continuation (``resume_from`` +
            the full token history from the relay ledger) and keep
            relaying from the splice, exactly-once (``_StreamState``
            drops any replayed index). Bounded by ``--max-stream-
            resumes`` spliced continuations and the remaining request
            deadline; past either bound — or for a stream whose chunks
            carried no index stamps (single-sequence replicas) — the
            legacy contract stands: the explicit terminal 502 event.
            Returns the final relayed status."""
            st, exc = sd.st, sd.exc
            dead = {rep}
            while True:
                t_detect = telemetry.now_ns()
                if not st.resumable():
                    # unstamped stream or a ledger hole: not spliceable
                    self._stream_abort(rep, exc)
                    self._end_stream(rid, rep, hop, 502)
                    return 502
                outcome = None
                if st.resumes >= fleet.max_stream_resumes:
                    outcome = "exhausted"
                # the deadline a continuation must fit inside: the
                # client's own body "timeout" when it set one, else the
                # router-level --request-timeout default (0 = unbounded)
                limit_s = 0.0
                t = body.get("timeout")
                if isinstance(t, (int, float)) \
                        and not isinstance(t, bool) and t > 0:
                    limit_s = float(t)
                elif fleet.request_timeout_s > 0:
                    limit_s = fleet.request_timeout_s
                remaining_s = (limit_s - (t_detect - t0_ns) / 1e9
                               if limit_s else 0.0)
                if outcome is None and limit_s and remaining_s <= 0.05:
                    outcome = "no_budget"
                rep2 = None
                if outcome is None:
                    st.resumes += 1
                    rep2 = fleet.pick(affinity_key(body), exclude=dead)
                    if rep2 is None:
                        outcome = "failed"
                if outcome is not None:
                    fleet.c_resumes.inc(outcome=outcome)
                    self._stream_abort(rep, exc)
                    self._end_stream(rid, rep, hop, 502)
                    return 502
                hop += 1
                rbody = dict(body)
                rbody.pop("resume_from", None)
                rbody.pop("resume_tokens", None)
                if st.n_tokens:
                    rbody["resume_from"] = st.n_tokens
                    rbody["resume_tokens"] = list(st.tokens)
                if limit_s:
                    rbody["timeout"] = round(remaining_s, 3)
                extra = {FLEET_RID_HEADER: rid,
                         FLEET_HOP_HEADER: str(hop),
                         RESUME_FROM_HEADER: str(st.n_tokens),
                         # router-authored re-dispatch: without this the
                         # continuation lands on the new replica as
                         # "anon" and the tenant's usage splits across
                         # identities mid-stream
                         TENANT_HEADER: self._tenant or tenancy.ANON}
                # prefer pulling the prefix (prompt + history) over the
                # KV wire: any advertising peer serves — including the
                # dying donor while it still answers, or a prefill-role
                # replica — with the replica's recompute fallback
                # covering every refusal
                donor = fleet.kv_donor(affinity_key(body), rep2)
                if donor is not None:
                    extra[KV_PEER_HEADER] = donor.name
                    t_don = telemetry.now_ns()
                    fleet.spans.emit_span(rid, "rt_kv_donor", t_don,
                                          t_don, replica=rep2.name,
                                          donor=donor.name)
                t_redispatch = telemetry.now_ns()
                rep2.begin_request()
                try:
                    try:
                        # the resume chaos sever point: an armed
                        # `resume` failpoint kills the re-dispatch
                        # exactly where a dying resume target would
                        failpoints.fire("resume")
                        conn, resp = self._open_upstream(
                            rep2, "POST", "/v1/chat/completions",
                            json.dumps(rbody).encode("utf-8"),
                            extra_headers=extra)
                    except (OSError, failpoints.FailpointError,
                            _UpstreamDied) as e:
                        if isinstance(e, _UpstreamDied) \
                                and e.code in ("draining", "queue_full"):
                            rep2.note_unready(e.code)
                        else:
                            rep2.note_failure()
                        fleet.c_resumes.inc(outcome="failed")
                        dead.add(rep2)
                        rep, exc = rep2, e
                        continue  # another attempt if the budget allows
                    rep2.note_success()
                    fleet.c_dispatch.inc(replica=rep2.name)
                    st.upstream_error = False
                    st.resume_t = (t_detect, t_redispatch,
                                   telemetry.now_ns(), st.n_tokens)
                    try:
                        return self._relay_response(
                            rep2, conn, resp, rid=rid, hop=hop,
                            t0_ns=t0_ns, st=st)
                    except _StreamDied as sd2:
                        if st.resume_t is not None:
                            # died before one continued event: the
                            # splice never happened — attempt failed
                            st.resume_t = None
                            fleet.c_resumes.inc(outcome="failed")
                        dead.add(rep2)
                        rep, exc = rep2, sd2.exc
                        continue
                finally:
                    rep2.end_request()

        def _proxy_buffered(self, method: str, path: str,
                            body: bytes | None) -> None:
            """Relay a small non-completion resource (/v1/models) with
            one failover: buffered, so any pre-client failure retries."""
            tried: set = set()
            for _ in range(2):
                rep = fleet.pick(None, exclude=tried)
                if rep is None:
                    break
                tried.add(rep)
                try:
                    conn, resp = self._open_upstream(rep, method, path,
                                                     body)
                except _UpstreamDied:
                    rep.note_failure()
                    continue
                rep.note_success()
                try:
                    data = resp.read()
                finally:
                    conn.close()
                self._count(resp.status)
                self.send_response(resp.status)
                for k, v in resp.getheaders():
                    if k in _RELAY_HEADERS and k != "Content-Length":
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            reason, code = fleet.unready_reason()
            self._json(503, {"error": reason, "code": code},
                       headers=backpressure_headers(503))

        # -- routes ---------------------------------------------------------

        def _fleet_timeline(self) -> None:
            """``GET /debug/fleet/timeline`` — pull every replica's live
            ``/debug/flight`` and join it with the router span ring into
            one Chrome trace (``flightrec.fleet_chrome_trace``). A
            replica that cannot answer contributes no track (its spans
            survive in the join only if another dump carries them); the
            trace's ``fleetJoin`` summary says how much joined."""
            dumps: dict[str, dict] = {}
            for rep in fleet.replicas:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=rep.connect_timeout_s)
                try:
                    conn.request("GET", "/debug/flight")
                    resp = conn.getresponse()
                    if resp.status == 200:
                        dumps[rep.name] = json.loads(resp.read())
                except (OSError, ValueError, http.client.HTTPException):
                    continue  # dead replica: absent track, not a 5xx
                finally:
                    conn.close()
            self._json(200, flightrec.fleet_chrome_trace(
                fleet.fleet_snapshot(), dumps))

        def _fleet_tenants(self) -> None:
            """``GET /debug/fleet/tenants`` — pull every replica's live
            ``/debug/tenants`` and join them into one fleet-wide usage
            view: per-replica registries verbatim, per-tenant totals
            summed across replicas, and a fleet Jain's index over the
            summed decode tokens. A replica that cannot answer
            contributes nothing (``replicas_joined`` says how many did);
            the router's own registry rides along so router-tier sheds
            (``router_queue_full``) are visible in the same body."""
            replicas: dict[str, dict] = {}
            for rep in fleet.replicas:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=rep.connect_timeout_s)
                try:
                    conn.request("GET", "/debug/tenants")
                    resp = conn.getresponse()
                    if resp.status == 200:
                        replicas[rep.name] = json.loads(resp.read())
                except (OSError, ValueError, http.client.HTTPException):
                    continue  # dead replica: absent entry, not a 5xx
                finally:
                    conn.close()
            totals: dict[str, dict] = {}
            for snap in replicas.values():
                for t, st in (snap.get("tenants") or {}).items():
                    agg = totals.setdefault(t, {})
                    for k, v in st.items():
                        if isinstance(v, (int, float)):
                            agg[k] = agg.get(k, 0) + v
                        elif isinstance(v, dict) and k == "sheds":
                            sh = agg.setdefault("sheds", {})
                            for r, n in v.items():
                                sh[r] = sh.get(r, 0) + n
            self._json(200, {
                "replicas_joined": len(replicas),
                "replicas": replicas,
                "tenants": totals,
                "fleet_jain_index": tenancy.jain_index(
                    st.get("decode_tokens", 0)
                    for st in totals.values()),
                "router": tenancy.registry().snapshot()})

        def do_GET(self):
            self._fleet_rid = None  # keep-alive: no stale POST echo
            self._tenant = None
            path = self.path.split("?", 1)[0]
            if path in ("/health", "/healthz"):
                self._json(200, {"status": "ok"})
            elif path == "/readyz":
                ready, reason, code = fleet.readiness()
                self._json(
                    200 if ready else 503,
                    {"status": "ok" if ready else "unready",
                     "reason": reason, "code": code},
                    headers=None if ready else backpressure_headers(503))
            elif path == "/metrics":
                if fleet.slo is not None:
                    # scrape-time evaluation keeps the compliance/burn
                    # gauges current without a timer thread of their own
                    fleet.slo.evaluate()
                self._count(200)
                body = telemetry.registry().render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path == "/debug/fleet":
                self._json(200, fleet.fleet_snapshot())
            elif path == "/debug/fleet/timeline":
                self._fleet_timeline()
            elif path == "/debug/fleet/tenants":
                self._fleet_tenants()
            elif path == "/debug/slo":
                if fleet.slo is None:
                    self._json(404, {"error": "no SLO objectives "
                                              "configured (start the "
                                              "router with --slo)"})
                else:
                    self._json(200, fleet.slo.evaluate())
            elif path == "/v1/models":
                self._proxy_buffered("GET", "/v1/models", None)
            else:
                self._json(404, {"error": "not found", "path": self.path,
                                 "routes": list(_ROUTES)})

        def do_POST(self):
            self._fleet_rid = None
            t_recv = telemetry.now_ns()  # rt_queue span origin
            path = self.path.split("?", 1)[0]
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = 0
            raw = b""
            if 0 < length <= (1 << 22):
                raw = self.rfile.read(length)
            elif length:
                # never forward a body we refused to read: an explicit
                # 413, and drop the connection instead of draining 4 MiB
                self.close_connection = True
                self._json(413, {"error": f"request body too large "
                                          f"({length} bytes; limit "
                                          f"{1 << 22})"})
                return
            if path != "/v1/chat/completions":
                self._json(404, {"error": "not found", "path": self.path,
                                 "routes": list(_ROUTES)})
                return
            try:
                body = json.loads(raw or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError:
                # malformed enough that no affinity key exists; the
                # replica owns the full validation answer
                body = {}
            # fleet trace identity: honor a sanitary client id, else mint
            rid = fleet.mint_rid(self.headers.get(FLEET_RID_HEADER))
            self._fleet_rid = rid
            # tenant identity: sanitized + cardinality-bounded here (the
            # router's own registry attributes router-tier decisions);
            # the canonical label rides every upstream hop and answer
            tenant = tenancy.registry().resolve(
                self.headers.get(TENANT_HEADER))
            self._tenant = tenant
            if not fleet.admit():
                if fleet.is_draining():
                    fleet.spans.emit_span(rid, "rt_queue", t_recv,
                                          telemetry.now_ns(),
                                          outcome="draining",
                                          tenant=tenant)
                    self._json(503, {"error": "router is draining",
                                     "code": "draining"},
                               headers=backpressure_headers(503))
                    return
                fleet.c_shed.inc()
                tenancy.registry().note_shed(tenant, "router_queue_full")
                if fleet.slo is not None:
                    fleet.slo.observe_outcome(shed=True, tenant=tenant)
                fleet.spans.emit_span(rid, "rt_queue", t_recv,
                                      telemetry.now_ns(), outcome="shed",
                                      tenant=tenant,
                                      reason="router_queue_full")
                self._json(429, {"error": f"router at --max-queue "
                                          f"({fleet.max_inflight} in "
                                          f"flight); retry later",
                                 "code": "queue_full"},
                           headers=backpressure_headers(429))
                return
            # request receipt → admission decision: the router's queue
            # phase (near-zero here — admission is one lock — but the
            # span anchors the request's flow at the router tier)
            fleet.spans.emit_span(rid, "rt_queue", t_recv,
                                  telemetry.now_ns(), outcome="admitted",
                                  tenant=tenant)
            shed = False
            try:
                shed = self._dispatch_completion(raw, body, rid, t_recv)
            finally:
                fleet.release()
            if fleet.slo is not None:
                fleet.slo.observe_outcome(shed=shed, tenant=tenant)

        def _note_eject(self, rid: str, rep: Replica, hop: int) -> None:
            """Instant ``rt_eject`` marker when a dispatch failure trips
            the breaker (state observed down right after note_failure)."""
            if rep.snapshot()["state"] == "down":
                now = telemetry.now_ns()
                fleet.spans.emit_span(rid, "rt_eject", now, now,
                                      replica=rep.name, hop=hop)

        def _prefill_warm(self, body: dict, rid: str) -> Replica | None:
            """Explicit disaggregation: run the prompt (one token, no
            stream) on the least-loaded prefill-role replica so its
            paged pool holds the prefix, then name it as the KV donor
            for the decode dispatch. Best-effort on every path — a
            failed or refused warm-up just means the decode replica
            prefills locally."""
            pre = fleet.prefill_replicas()
            if not pre:
                return None
            rep = min(pre, key=lambda r: r.load_score())
            warm = dict(body)
            warm["max_tokens"] = 1
            warm["stream"] = False
            warm.pop("timing", None)
            t0 = telemetry.now_ns()
            rep.begin_request()
            try:
                conn, resp = self._open_upstream(
                    rep, "POST", "/v1/chat/completions",
                    json.dumps(warm).encode("utf-8"),
                    extra_headers={FLEET_RID_HEADER: rid,
                                   FLEET_HOP_HEADER: "0",
                                   # warm-up work bills to its caller,
                                   # not to "anon" on the prefill pod
                                   TENANT_HEADER: self._tenant
                                   or tenancy.ANON})
                try:
                    resp.read()
                finally:
                    conn.close()
                rep.note_success()
                return rep
            except _UpstreamDied:
                return None
            finally:
                rep.end_request()
                fleet.spans.emit_span(rid, "rt_prefill", t0,
                                      telemetry.now_ns(),
                                      replica=rep.name)

        def _dispatch_completion(self, raw: bytes, body: dict,
                                 rid: str, t0_ns: int) -> bool:
            """Dispatch one admitted completion (with one cross-replica
            retry); returns True when the request was ultimately SHED
            (queue_full) — the caller's SLO shed-rate observation."""
            key = affinity_key(body)
            tried: set = set()
            last: _UpstreamDied | None = None
            ns_failed = 0  # wall burned on failed hops before serving
            self._t_first_ns = None
            for attempt in range(2):
                t_pick = telemetry.now_ns()
                rep = fleet.pick(key, exclude=tried)
                if rep is None:
                    break
                tried.add(rep)
                if attempt:
                    fleet.c_retries.inc()
                # dispatch attempts by hop index: hop="1"+ are retry
                # hops — the same index the X-Dllama-Hop header carries
                fleet.c_retry_hops.inc(hop=str(attempt))
                snap = rep.snapshot()
                # the dispatch decision as an instant marker, carrying
                # the probe snapshot that justified the pick
                fleet.spans.emit_span(
                    rid, "rt_dispatch", t_pick, t_pick,
                    replica=rep.name, hop=attempt, state=snap["state"],
                    load=round(snap["queue_depth"]
                               + snap["engine_inflight"]
                               + snap["router_inflight"], 3))
                extra = {FLEET_RID_HEADER: rid,
                         FLEET_HOP_HEADER: str(attempt),
                         TENANT_HEADER: self._tenant or tenancy.ANON}
                if attempt == 0 and key is not None \
                        and not rep.holds_prefix(key):
                    # fleet-global prefix reuse: a peer advertising this
                    # key becomes the KV donor; with none, explicit
                    # disaggregation warms a prefill-role replica first.
                    # First hop only — a retry hop already paid for one
                    # migration attempt and must not stack another wire
                    # wait on a degraded fleet
                    donor = fleet.kv_donor(key, rep)
                    if donor is None:
                        donor = self._prefill_warm(body, rid)
                        if donor is rep:
                            donor = None
                    if donor is not None:
                        extra[KV_PEER_HEADER] = donor.name
                        t_don = telemetry.now_ns()
                        fleet.spans.emit_span(rid, "rt_kv_donor", t_don,
                                              t_don, replica=rep.name,
                                              donor=donor.name)
                rep.begin_request()
                t_hop0 = telemetry.now_ns()
                try:
                    try:
                        conn, resp = self._open_upstream(
                            rep, "POST", "/v1/chat/completions", raw,
                            extra_headers=extra)
                    except _UpstreamDied as e:
                        t_fail = telemetry.now_ns()
                        ns_failed += t_fail - t_hop0
                        fleet.h_connect.record((t_fail - t_hop0) / 1e6,
                                               replica=rep.name)
                        fleet.spans.emit_span(
                            rid, "rt_retry", t_hop0, t_fail,
                            replica=rep.name, hop=attempt,
                            code=e.code or "connect")
                        if e.code in ("draining", "queue_full"):
                            # an explicit backpressure answer: the
                            # replica is alive — reclassify, don't eject
                            rep.note_unready(e.code)
                        else:
                            rep.note_failure()
                            self._note_eject(rid, rep, attempt)
                        last = e
                        continue
                    t_conn = telemetry.now_ns()
                    fleet.h_connect.record((t_conn - t_hop0) / 1e6,
                                           replica=rep.name)
                    fleet.spans.emit_span(rid, "rt_connect", t_hop0,
                                          t_conn, replica=rep.name,
                                          hop=attempt)
                    rep.note_success()
                    fleet.c_dispatch.inc(replica=rep.name)
                    if attempt:
                        # the serving hop follows >=1 failed hop: record
                        # the retry tax this request paid, once
                        fleet.h_retry.record(ns_failed / 1e6)
                    try:
                        status = self._relay_response(
                            rep, conn, resp, rid=rid, hop=attempt,
                            t0_ns=t0_ns)
                    except _UpstreamDied as e:
                        # buffered body died before the client saw a
                        # byte: feed the breaker and retry
                        ns_failed += telemetry.now_ns() - t_hop0
                        fleet.spans.emit_span(
                            rid, "rt_retry", t_hop0, telemetry.now_ns(),
                            replica=rep.name, hop=attempt,
                            code="mid_body")
                        rep.note_failure()
                        self._note_eject(rid, rep, attempt)
                        last = e
                        continue
                    except _StreamDied as sd:
                        # the stream died with bytes already relayed: a
                        # fresh retry would duplicate the transcript —
                        # splice a continuation instead (or send the
                        # explicit terminal 502 past the resume budget)
                        try:
                            status = self._resume_stream(
                                body, rid, rep, attempt, sd, t0_ns)
                        except (BrokenPipeError, ConnectionResetError):
                            status = "client_disconnect"
                            self.close_connection = True
                    except (BrokenPipeError, ConnectionResetError):
                        status = "client_disconnect"
                        self.close_connection = True
                    self._count(status)
                    return False
                finally:
                    rep.end_request()
            # retry budget exhausted or no replica at all
            if last is not None and last.status is not None \
                    and len(tried) >= len(fleet.replicas):
                # single-replica degradation: the upstream's own 5xx
                # passes through unmangled (status, headers, body)
                self._count(last.status)
                self.send_response(last.status)
                if self._fleet_rid:
                    self.send_header(FLEET_RID_HEADER, self._fleet_rid)
                for k, v in (last.headers or ()):
                    if k in _RELAY_HEADERS and k != "Content-Length":
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(last.body)))
                self.end_headers()
                self.wfile.write(last.body)
                return False
            if last is not None:
                self._json(502, {"error": f"dispatch failed on "
                                          f"{len(tried)} replica(s): "
                                          f"{last}",
                                 "code": "crashed"},
                           headers=backpressure_headers(503))
                return False
            reason, code = fleet.unready_reason()
            if code == "queue_full":
                fleet.c_shed.inc()
                # fleet-saturated shed is attributable too: same
                # router-tier reason as the --max-queue bound
                tenant = self._tenant or tenancy.ANON
                tenancy.registry().note_shed(tenant, "router_queue_full")
                fleet.spans.emit_span(rid, "rt_queue", t0_ns,
                                      telemetry.now_ns(), outcome="shed",
                                      tenant=tenant,
                                      reason="router_queue_full")
                self._json(429, {"error": reason, "code": code},
                           headers=backpressure_headers(429))
                return True
            self._json(503, {"error": reason, "code": code},
                       headers=backpressure_headers(503))
            return False

    return RouterHandler


def run_router(args) -> int:
    """``python -m dllama_tpu router --replica URL [--replica URL ...]``
    — pure host tier: no model, no tokenizer, no device; never
    initializes a jax backend."""
    import os
    import signal

    replicas = list(args.replica or [])
    if not replicas:
        raise SystemExit("router mode needs at least one --replica URL "
                         "(repeat the flag per replica)")
    if failpoints.configure_from_env():
        print("💣 fault injection armed from DLLAMA_FAILPOINTS="
              f"{os.environ['DLLAMA_FAILPOINTS']}")
    slo_objectives = None
    if getattr(args, "slo", None):
        try:
            slo_objectives = slo.load_slo(args.slo)
        except ValueError as e:
            # a typo'd SLO must fail at startup with the objective
            # named, not silently never alarm
            raise SystemExit(f"--slo: {e}")
    fleet = FleetRouter(
        replicas,
        probe_interval_s=getattr(args, "probe_interval", 2.0) or 2.0,
        max_inflight=getattr(args, "max_queue", 0) or 0,
        max_stream_resumes=getattr(args, "max_stream_resumes", 1),
        request_timeout_s=getattr(args, "request_timeout", 0.0) or 0.0,
        slo_objectives=slo_objectives)
    if slo_objectives:
        print("🎯 SLO observatory: "
              + ", ".join(f"{k}≤{v:g}"
                          for k, v in slo_objectives.items())
              + " (burn windows "
              + "/".join(label for label, _ in slo.WINDOWS)
              + "; GET /debug/slo)")
    server = ThreadingHTTPServer((args.host, args.port),
                                 make_router_handler(fleet))
    print(f"🕸️ fleet router: {len(fleet.replicas)} replicas "
          f"({', '.join(r.name for r in fleet.replicas)}), probe every "
          f"~{fleet.probe_interval_s:g}s"
          + (f", shed beyond {fleet.max_inflight} in flight"
             if fleet.max_inflight else "")
          + (f", streams survive ≤{fleet.max_stream_resumes} replica "
             f"death(s) mid-flight"
             if fleet.max_stream_resumes else ""))

    def _on_sigterm(signum, frame):
        print("🛑 SIGTERM: router draining (readyz → 503, in-flight "
              "streams finish)", flush=True)
        fleet.begin_drain()
        threading.Thread(target=server.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (embedded/test usage)
    stats_every = getattr(args, "stats", 0) or 0
    if stats_every:
        def _stats_loop():  # dlint: owner=any
            while not fleet._stop.wait(stats_every):
                if fleet.slo is not None:
                    fleet.slo.evaluate()  # refresh gauges for the line
                print(telemetry.stats_line(window_s=stats_every),
                      flush=True)
        threading.Thread(target=_stats_loop, daemon=True,
                         name="router-stats").start()
    print(f"🕸️ listening on http://{args.host}:{args.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        fleet.close()
    return 0
