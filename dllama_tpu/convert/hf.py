"""HF safetensors / Meta .pth checkpoint → .m converter.

Behavior parity with the reference converter (reference: converter/convert-hf.py
for the plan + config mapping, converter/convert-llama.py for Meta checkpoints,
converter/writer.py for tensor encoding), re-done as a declarative tensor plan
over vectorized numpy codecs (:mod:`dllama_tpu.formats.quants`). No torch
needed for the safetensors path; the Meta path uses torch only to unpickle.
"""

from __future__ import annotations

import json
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable

import numpy as np

from ..formats.mfile import (ArchType, HiddenAct, ModelFile, RopeType,
                             write_header, write_manifest)
from ..formats.quants import F16, F32, Q40, Q80, quantize_q40, quantize_q80

FLOAT_TYPE_BY_NAME = {"f32": F32, "f16": F16, "q40": Q40, "q80": Q80}
FLOAT_NAME_BY_TYPE = {v: k for k, v in FLOAT_TYPE_BY_NAME.items()}

ARCH_BY_MODEL_TYPE = {
    # reference: convert-hf.py:144-152; the MoE entries are ours (the
    # reference can convert Mixtral experts but not run them)
    "llama": ArchType.LLAMA,
    "mistral": ArchType.LLAMA,
    "mixtral": ArchType.LLAMA,
    "qwen3": ArchType.QWEN3,
    "qwen3_moe": ArchType.QWEN3,
}

HIDDEN_ACT_BY_NAME = {"gelu": HiddenAct.GELU, "silu": HiddenAct.SILU}


def _keyed_checksums(path: str | Path, crcs: list[int]) -> dict[str, int]:
    """Attach walker keys to crc32s accumulated in emission order — the
    .m tensor walk IS the converter's emission order, and the directory
    walk reads only the header, so the manifest costs zero re-reads of a
    multi-GB model (write_manifest's recompute path would read it all
    again)."""
    with ModelFile.open(path, load_checksums=False) as mf:
        keys = list(mf.tensors)
    if len(keys) != len(crcs):  # a plan/walk disagreement is a format bug
        raise ValueError(f"converter emitted {len(crcs)} tensors but the "
                         f"walker found {len(keys)} — refusing to write a "
                         f"misaligned checksum manifest")
    return dict(zip(keys, crcs))


def parse_float_type(name: str) -> int:
    try:
        return FLOAT_TYPE_BY_NAME[name]
    except KeyError:
        raise ValueError(f"unsupported float type {name!r}; "
                         f"expected one of {sorted(FLOAT_TYPE_BY_NAME)}") from None


def permute_rope_rows(w: np.ndarray, n_heads: int) -> np.ndarray:
    """Reorder Q/K projection rows from HF's half-split rotary layout to the
    interleaved layout the llama rope kernel expects (reference:
    convert-hf.py:12-15). Operates on ``[out, in]`` weight matrices where
    ``out = n_heads * head_dim``."""
    out_dim = w.shape[0]
    head_dim = out_dim // n_heads
    return (w.reshape(n_heads, 2, head_dim // 2, *w.shape[1:])
            .swapaxes(1, 2)
            .reshape(w.shape))


def encode_tensor(x: np.ndarray, float_type: int) -> bytes:
    """Encode a tensor body the way the reference writer does
    (reference: converter/writer.py:29-107)."""
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if float_type == F32:
        return flat.tobytes()
    if float_type == F16:
        return flat.astype(np.float16).tobytes()
    if float_type == Q40:
        return quantize_q40(flat)
    if float_type == Q80:
        return quantize_q80(flat)
    raise ValueError(f"unsupported target float type {float_type}")


# ---------------------------------------------------------------------------
# config.json → header params
# ---------------------------------------------------------------------------


def load_hf_config(folder: str | Path, weight_float_type: int) -> dict:
    """Map an HF ``config.json`` to .m header params keyed by
    :class:`~dllama_tpu.formats.mfile.HeaderKey` names
    (reference: convert-hf.py:178-229)."""
    folder = Path(folder)
    with open(folder / "config.json", encoding="utf-8") as f:
        cfg = json.load(f)

    model_type = cfg["model_type"]
    if model_type not in ARCH_BY_MODEL_TYPE:
        raise ValueError(f"unsupported arch type: {model_type}")

    params: dict = {
        "version": 0,
        "arch_type": int(ARCH_BY_MODEL_TYPE[model_type]),
        "hidden_act": int(HIDDEN_ACT_BY_NAME[cfg["hidden_act"]]),
        "dim": cfg["hidden_size"],
        "hidden_dim": cfg["intermediate_size"],
        "n_layers": cfg["num_hidden_layers"],
        "n_heads": cfg["num_attention_heads"],
        "n_kv_heads": cfg["num_key_value_heads"],
        "weight_float_type": weight_float_type,
        "seq_len": cfg["max_position_embeddings"],
        "vocab_size": cfg["vocab_size"],
    }

    # Mixtral: num_local_experts; Qwen3-MoE: num_experts (+ the experts' own
    # hidden size in moe_intermediate_size, which becomes the header's
    # hidden_dim since MoE layers have no dense FFN)
    n_experts = cfg.get("num_local_experts") or cfg.get("num_experts")
    n_active = cfg.get("num_active_local_experts") or cfg.get("num_experts_per_tok")
    params["n_experts"] = int(n_experts) if n_experts else 0
    params["n_active_experts"] = int(n_active) if n_active else 0
    if params["n_experts"] > 0:
        if cfg.get("moe_intermediate_size"):
            params["hidden_dim"] = int(cfg["moe_intermediate_size"])
        # Mixtral always renormalizes the selected router weights; Qwen3-MoE
        # follows norm_topk_prob (HF Qwen3MoeConfig default: False)
        if model_type == "qwen3_moe":
            params["moe_norm_topk"] = int(bool(cfg.get("norm_topk_prob", False)))
            # Mixed dense/MoE stacks (some layers plain MLP) can't be
            # expressed in the .m layer plan, which assumes every layer is
            # MoE — converting one would write expert tensors for layers the
            # checkpoint doesn't have (advisor round-1 finding). Reject.
            sparse_step = int(cfg.get("decoder_sparse_step") or 1)
            mlp_only = list(cfg.get("mlp_only_layers") or [])
            if sparse_step != 1 or mlp_only:
                raise ValueError(
                    f"qwen3_moe with mixed dense/MoE layers is unsupported: "
                    f"decoder_sparse_step={sparse_step}, "
                    f"mlp_only_layers={mlp_only} — every layer must be MoE")
        else:
            params["moe_norm_topk"] = 1

    if cfg.get("rope_theta") is not None:
        params["rope_theta"] = int(cfg["rope_theta"])

    rs = cfg.get("rope_scaling")
    if rs is not None:
        if rs.get("rope_type") != "llama3":
            raise ValueError(f"unsupported rope scaling type {rs.get('rope_type')!r}")
        params["rope_scaling_factor"] = int(rs["factor"])
        params["rope_scaling_low_freq_factor"] = int(rs["low_freq_factor"])
        params["rope_scaling_high_freq_factory"] = int(rs["high_freq_factor"])
        params["rope_scaling_orig_max_seq_len"] = int(
            rs["original_max_position_embeddings"])
        params["rope_type"] = int(RopeType.LLAMA3_1)

    if cfg.get("head_dim") is not None:
        params["head_dim"] = cfg["head_dim"]

    eps = cfg.get("rms_norm_eps")
    if eps is not None:
        if eps == 1e-5:
            params["norm_epsilon"] = 5
        elif eps == 1e-6:
            params["norm_epsilon"] = 6
        else:
            raise ValueError(f"unsupported rms_norm_eps {eps}")
    return params


# ---------------------------------------------------------------------------
# tensor plan
# ---------------------------------------------------------------------------


@dataclass
class PlanItem:
    """One tensor to emit: candidate source keys (first found wins — the
    second entry expresses lm_head→embedding weight tying,
    reference: convert-hf.py:101-102), target encoding, optional transform."""

    keys: tuple[str, ...]
    float_type: int
    transform: Callable[[np.ndarray], np.ndarray] | None = None


def hf_tensor_plan(params: dict) -> list[PlanItem]:
    """The .m tensor emission order for an HF checkpoint
    (reference: convert-hf.py:58-102; consumed by llm.cpp:499-539 and our
    :meth:`dllama_tpu.formats.mfile.ModelFile._walk`)."""
    wt = params["weight_float_type"]
    arch = ArchType(params["arch_type"])
    n_heads = params["n_heads"]
    n_kv_heads = params["n_kv_heads"]

    def permute_q(w: np.ndarray) -> np.ndarray:
        return permute_rope_rows(w, n_heads)

    def permute_k(w: np.ndarray) -> np.ndarray:
        return permute_rope_rows(w, n_kv_heads)

    # Qwen3 ships rotary halves directly (neox rope) — no permutation there.
    q_tr = permute_q if arch == ArchType.LLAMA else None
    k_tr = permute_k if arch == ArchType.LLAMA else None

    plan = [PlanItem(("model.embed_tokens.weight",), F32)]
    for l in range(params["n_layers"]):
        pre = f"model.layers.{l}"
        plan.append(PlanItem((f"{pre}.self_attn.q_proj.weight",), wt, q_tr))
        plan.append(PlanItem((f"{pre}.self_attn.k_proj.weight",), wt, k_tr))
        plan.append(PlanItem((f"{pre}.self_attn.v_proj.weight",), wt))
        plan.append(PlanItem((f"{pre}.self_attn.o_proj.weight",), wt))
        if params["n_experts"] > 0:
            # Router first — OUR extension (block_moe_gate; the reference
            # converter omits it, making its MoE files unrunnable) — then the
            # experts in the reference's w3/w1/w2 order (convert-hf.py:73-80).
            # Key pairs cover Mixtral (block_sparse_moe.*) and Qwen3-MoE
            # (mlp.gate / mlp.experts.*.{gate,down,up}_proj) checkpoints.
            plan.append(PlanItem((f"{pre}.block_sparse_moe.gate.weight",
                                  f"{pre}.mlp.gate.weight"), F32))
            for e in range(params["n_experts"]):
                mx = f"{pre}.block_sparse_moe.experts.{e}"
                qw = f"{pre}.mlp.experts.{e}"
                plan.append(PlanItem((f"{mx}.w3.weight",
                                      f"{qw}.up_proj.weight"), wt))
                plan.append(PlanItem((f"{mx}.w1.weight",
                                      f"{qw}.gate_proj.weight"), wt))
                plan.append(PlanItem((f"{mx}.w2.weight",
                                      f"{qw}.down_proj.weight"), wt))
        else:
            plan.append(PlanItem((f"{pre}.mlp.gate_proj.weight",), wt))  # w1
            plan.append(PlanItem((f"{pre}.mlp.down_proj.weight",), wt))  # w2
            plan.append(PlanItem((f"{pre}.mlp.up_proj.weight",), wt))    # w3
        if arch == ArchType.QWEN3:
            plan.append(PlanItem((f"{pre}.self_attn.q_norm.weight",), F32))
            plan.append(PlanItem((f"{pre}.self_attn.k_norm.weight",), F32))
        plan.append(PlanItem((f"{pre}.input_layernorm.weight",), F32))
        plan.append(PlanItem((f"{pre}.post_attention_layernorm.weight",), F32))
    plan.append(PlanItem(("model.norm.weight",), F32))
    plan.append(PlanItem(("lm_head.weight", "model.embed_tokens.weight"), wt))
    return plan


class SafetensorsDirectory:
    """Lazy multi-file safetensors reader: keeps at most one shard open,
    resolves key→file via the (tiny) headers up front — unlike the reference's
    sequential guessing walk (convert-hf.py:104-136), the index is exact."""

    def __init__(self, files: Iterable[str | Path]):
        from safetensors import safe_open
        self._safe_open = safe_open
        self.files = [str(f) for f in files]
        if not self.files:
            raise ValueError("no safetensors files given")
        self.key_to_file: dict[str, str] = {}
        for path in self.files:
            with safe_open(path, framework="numpy", device="cpu") as f:
                for key in f.keys():
                    self.key_to_file[key] = path
        self._open_path: str | None = None
        self._open_file = None

    def __contains__(self, key: str) -> bool:
        return key in self.key_to_file

    def get(self, key: str) -> np.ndarray:
        path = self.key_to_file[key]
        if path != self._open_path:
            if self._open_file is not None:
                self._open_file.__exit__(None, None, None)
            self._open_file = self._safe_open(path, framework="numpy", device="cpu")
            self._open_file.__enter__()
            self._open_path = path
        t = self._open_file.get_tensor(key)
        # bf16 arrives as an ml_dtypes.bfloat16 ndarray; astype handles it
        return np.asarray(t).astype(np.float32)

    def close(self) -> None:
        if self._open_file is not None:
            self._open_file.__exit__(None, None, None)
            self._open_file = None
            self._open_path = None


def convert_hf(source_dir: str | Path, weight_float_type: int | str,
               output_path: str | Path, *, progress: bool = True) -> str:
    """Convert an HF safetensors model directory to a .m file
    (reference: convert-hf.py main flow)."""
    if isinstance(weight_float_type, str):
        weight_float_type = parse_float_type(weight_float_type)
    source_dir = Path(source_dir)
    params = load_hf_config(source_dir, weight_float_type)

    files = sorted(p for p in source_dir.iterdir()
                   if p.name.endswith(".safetensors") and not p.name.startswith("."))
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {source_dir}")
    src = SafetensorsDirectory(files)

    plan = hf_tensor_plan(params)
    crcs: list[int] = []
    try:
        with open(output_path, "wb") as out:
            write_header(out, params)
            for item in plan:
                key = next((k for k in item.keys if k in src), None)
                if key is None:
                    raise KeyError(f"tensor {item.keys[0]} not found in checkpoint")
                tensor = src.get(key)
                if item.transform is not None:
                    tensor = item.transform(tensor)
                if progress:
                    print(f"🔶 Writing {key} {tensor.shape} as "
                          f"{FLOAT_NAME_BY_TYPE[item.float_type]}")
                data = encode_tensor(tensor, item.float_type)
                crcs.append(zlib.crc32(data) & 0xFFFFFFFF)
                out.write(data)
    finally:
        src.close()
    # per-tensor crc32 sidecar: the streaming loader verifies each tensor
    # against it at load and names the exact corrupt tensor on mismatch
    sums = write_manifest(output_path, _keyed_checksums(output_path, crcs))
    if progress:
        print(f"🔏 checksum manifest → {sums}")
    return str(output_path)


# ---------------------------------------------------------------------------
# Meta (consolidated.*.pth) checkpoints
# ---------------------------------------------------------------------------


def convert_meta_llama(source_dir: str | Path, weight_float_type: int | str,
                       output_path: str | Path, *, progress: bool = True) -> str:
    """Convert a Meta-format Llama checkpoint (params.json +
    consolidated.NN.pth shards) to .m (reference: convert-llama.py:11-121).

    Shards are column-chunks for row-parallel tensors (embedding, wo, w2 —
    concat on axis 1) and row-chunks for the rest (concat on axis 0); 1-D
    tensors are replicated. Shards are opened with ``mmap=True`` so tensor
    storages stay lazy; peak memory is one tensor × n_shards, not the model.
    """
    import torch  # CPU-only unpickle of the .pth shards

    if isinstance(weight_float_type, str):
        weight_float_type = parse_float_type(weight_float_type)
    source_dir = Path(source_dir)
    with open(source_dir / "params.json", encoding="utf-8") as f:
        meta = json.load(f)
    if meta.get("vocab_size", -1) < 1:
        raise ValueError("vocab_size missing/invalid in params.json")
    if meta.get("max_seq_len") is None:
        raise ValueError("max_seq_len is required in params.json")

    shard_paths = sorted(source_dir.glob("consolidated.*.pth"))
    if not shard_paths:
        raise FileNotFoundError(f"no consolidated.*.pth in {source_dir}")
    shards = [torch.load(p, map_location="cpu", weights_only=True, mmap=True)
              for p in shard_paths]

    n_layers = meta["n_layers"]
    params: dict = {
        "version": 0,
        "arch_type": int(ArchType.LLAMA),
        "hidden_act": int(HiddenAct.SILU),
        "dim": meta["dim"],
        "hidden_dim": shards[0]["layers.0.feed_forward.w1.weight"].shape[0]
                      * len(shards),
        "n_layers": n_layers,
        "n_heads": meta["n_heads"],
        "n_kv_heads": meta.get("n_kv_heads") or meta["n_heads"],
        "n_experts": 0,
        "n_active_experts": 0,
        "weight_float_type": weight_float_type,
        "seq_len": meta["max_seq_len"],
        "vocab_size": meta["vocab_size"],
    }
    if "rope_theta" in meta:
        params["rope_theta"] = int(meta["rope_theta"])
    if "norm_eps" in meta:
        if meta["norm_eps"] == 1e-5:
            params["norm_epsilon"] = 5
        elif meta["norm_eps"] == 1e-6:
            params["norm_epsilon"] = 6

    names: list[str] = ["tok_embeddings.weight"]
    for l in range(n_layers):
        names += [f"layers.{l}.attention.wq.weight",
                  f"layers.{l}.attention.wk.weight",
                  f"layers.{l}.attention.wv.weight",
                  f"layers.{l}.attention.wo.weight",
                  f"layers.{l}.feed_forward.w1.weight",
                  f"layers.{l}.feed_forward.w2.weight",
                  f"layers.{l}.feed_forward.w3.weight",
                  f"layers.{l}.attention_norm.weight",
                  f"layers.{l}.ffn_norm.weight"]
    names += ["norm.weight", "output.weight"]

    col_chunked = {"tok_embeddings.weight"}
    f32_always = {"tok_embeddings.weight", "norm.weight"}

    def merged(name: str) -> np.ndarray:
        parts = [np.asarray(s[name].to(torch.float32).numpy()) for s in shards]
        if len(parts) == 1 or parts[0].ndim == 1:
            return parts[0]
        axis = 1 if (name in col_chunked or name.endswith(".attention.wo.weight")
                     or name.endswith(".feed_forward.w2.weight")) else 0
        return np.concatenate(parts, axis=axis)

    crcs: list[int] = []
    with open(output_path, "wb") as out:
        write_header(out, params)
        for name in names:
            is_f32 = (name in f32_always or name.endswith(".attention_norm.weight")
                      or name.endswith(".ffn_norm.weight"))
            ft = F32 if is_f32 else weight_float_type
            tensor = merged(name)
            if progress:
                print(f"🔶 Writing {name} {tensor.shape} as {FLOAT_NAME_BY_TYPE[ft]}")
            data = encode_tensor(tensor, ft)
            crcs.append(zlib.crc32(data) & 0xFFFFFFFF)
            out.write(data)
    sums = write_manifest(output_path, _keyed_checksums(output_path, crcs))
    if progress:
        print(f"🔏 checksum manifest → {sums}")
    return str(output_path)


def default_output_name(name: str, weight_float_type: int) -> str:
    return f"dllama_model_{name}_{FLOAT_NAME_BY_TYPE[weight_float_type]}.m"
