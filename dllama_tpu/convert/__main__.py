"""Converter CLI — ``python -m dllama_tpu.convert <subcommand>``.

Subcommands mirror the reference converter scripts
(reference: converter/convert-hf.py, convert-llama.py,
convert-tokenizer-{hf,llama2,llama3}.py):

    python -m dllama_tpu.convert hf <hf_dir> <f32|q40|q80> <name>
    python -m dllama_tpu.convert llama <meta_dir> <f32|q40|q80>
    python -m dllama_tpu.convert tokenizer-hf <hf_dir> <name>
    python -m dllama_tpu.convert tokenizer-llama2 <dir>
    python -m dllama_tpu.convert tokenizer-llama3 <tokenizer.model>
"""

from __future__ import annotations

import argparse
import os
import sys

from .hf import (
    FLOAT_TYPE_BY_NAME,
    convert_hf,
    convert_meta_llama,
    default_output_name,
    parse_float_type,
)
from .tokenizers import (
    convert_tokenizer_hf,
    convert_tokenizer_llama2,
    convert_tokenizer_llama3,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="dllama_tpu.convert")
    sub = p.add_subparsers(dest="cmd", required=True)

    hf = sub.add_parser("hf", help="HF safetensors dir -> .m")
    hf.add_argument("source")
    hf.add_argument("float_type", choices=sorted(FLOAT_TYPE_BY_NAME))
    hf.add_argument("name")
    hf.add_argument("--output", default=None)

    meta = sub.add_parser("llama", help="Meta consolidated.*.pth dir -> .m")
    meta.add_argument("source")
    meta.add_argument("float_type", choices=sorted(FLOAT_TYPE_BY_NAME))
    meta.add_argument("--output", default=None)

    th = sub.add_parser("tokenizer-hf", help="HF tokenizer dir -> .t")
    th.add_argument("source")
    th.add_argument("name")
    th.add_argument("--output", default=None)

    t2 = sub.add_parser("tokenizer-llama2", help="sentencepiece dir -> .t")
    t2.add_argument("source")
    t2.add_argument("--output", default="dllama_tokenizer_llama2.t")

    t3 = sub.add_parser("tokenizer-llama3", help="tiktoken tokenizer.model -> .t")
    t3.add_argument("source")
    t3.add_argument("--output", default="dllama_tokenizer_llama3.t")

    args = p.parse_args(argv)

    if args.cmd == "hf":
        ft = parse_float_type(args.float_type)
        out = args.output or default_output_name(args.name, ft)
        convert_hf(args.source, ft, out)
        print(f"✅ {out} created successfully")
    elif args.cmd == "llama":
        ft = parse_float_type(args.float_type)
        name = os.path.basename(os.path.normpath(args.source)).lower()
        out = args.output or default_output_name(name, ft)
        convert_meta_llama(args.source, ft, out)
        print(f"✅ {out} created successfully")
    elif args.cmd == "tokenizer-hf":
        out = args.output or f"dllama_tokenizer_{args.name}.t"
        convert_tokenizer_hf(args.source, out)
    elif args.cmd == "tokenizer-llama2":
        convert_tokenizer_llama2(args.source, args.output)
    elif args.cmd == "tokenizer-llama3":
        convert_tokenizer_llama3(args.source, args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())
