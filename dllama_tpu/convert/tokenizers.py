"""Tokenizer converters — HF fast / sentencepiece / tiktoken-file → .t.

Behavior parity with the reference converters (reference:
converter/convert-tokenizer-hf.py, convert-tokenizer-llama2.py,
convert-tokenizer-llama3.py), writing through
:func:`dllama_tpu.formats.tfile.write_tfile`.

The HF path resolves every vocab entry to raw bytes via the GPT-2 byte-level
unicode↔byte table; the llama3 path parses the tiktoken ``.model`` file format
(base64 token + rank per line) directly, so no tiktoken dependency is needed.
sentencepiece paths are gated on the library being installed.
"""

from __future__ import annotations

import base64
import json
from pathlib import Path

from ..formats.tfile import TokenizerData, write_tfile

# ---------------------------------------------------------------------------
# GPT-2 byte-level BPE unicode↔byte table
# ---------------------------------------------------------------------------


def unicode_to_bytes() -> dict[str, int]:
    """The GPT-2 printable-unicode → raw-byte mapping used by byte-level BPE
    vocabs (reference: convert-tokenizer-hf.py:12-23; the table is the inverse
    of GPT-2's bytes_to_unicode)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for c, b in zip(cs, bs)}


def token_str_to_bytes(token: str, table: dict[str, int]) -> bytes:
    """Decode one byte-level-BPE vocab string to raw bytes; characters outside
    the table (special tokens like ``<|eot_id|>``) pass through as UTF-8
    (reference: convert-tokenizer-hf.py:38-46)."""
    out = bytearray()
    for ch in token:
        if ch in table:
            out.append(table[ch])
        else:
            out.extend(ch.encode("utf-8"))
    return bytes(out)


# ---------------------------------------------------------------------------
# HF tokenizer directory → .t
# ---------------------------------------------------------------------------


def _open_json(path: Path) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def resolve_hf_vocab(token_strings: list[str]) -> tuple[list[bytes], list[float]]:
    """Byte-level vocab strings → (bytes, scores). Scores are ``-id`` so that
    greedy BPE prefers lower-id (earlier-learned) merges, matching the
    reference (convert-tokenizer-hf.py:46-47)."""
    table = unicode_to_bytes()
    vocab = [token_str_to_bytes(t, table) for t in token_strings]
    scores = [-float(i) for i in range(len(vocab))]
    return vocab, scores


def resolve_sentencepiece_vocab(model_path: str | Path
                                ) -> tuple[list[bytes], list[float], int, int]:
    """sentencepiece model → (bytes, scores, bos_id, eos_id)
    (reference: convert-tokenizer-hf.py:63-82). Requires sentencepiece."""
    try:
        from sentencepiece import SentencePieceProcessor
    except ImportError as e:
        raise RuntimeError(
            "sentencepiece is not installed in this environment; convert this "
            "tokenizer on a machine that has it, or use the HF fast-tokenizer "
            "path (tokenizer.json)") from e
    sp = SentencePieceProcessor(model_file=str(model_path))
    vocab: list[bytes] = []
    scores: list[float] = []
    for i in range(sp.vocab_size()):
        piece = sp.id_to_piece(i).replace("\u2581", " ")
        if len(piece) == 6 and piece.startswith("<0x") and piece.endswith(">"):
            b = bytes.fromhex(piece[3:-1])
        else:
            b = piece.encode("utf-8")
        vocab.append(b)
        scores.append(sp.get_score(i))
    return vocab, scores, sp.bos_id(), sp.eos_id()


def convert_tokenizer_hf(source_dir: str | Path, output_path: str | Path,
                         *, progress: bool = True) -> str:
    """HF tokenizer directory (tokenizer_config.json + tokenizer.json or
    tokenizer.model) → .t (reference: convert-tokenizer-hf.py)."""
    source_dir = Path(source_dir)
    tok_config = _open_json(source_dir / "tokenizer_config.json")
    cls = tok_config.get("tokenizer_class", "PreTrainedTokenizerFast")

    bos_id: int | None = None
    eos_ids: list[int] | None = None

    if cls in ("PreTrainedTokenizerFast", "LlamaTokenizerFast", "Qwen2Tokenizer"):
        from transformers import PreTrainedTokenizerFast
        tok = PreTrainedTokenizerFast(
            tokenizer_file=str(source_dir / "tokenizer.json"))
        n = len(tok.get_vocab())
        strings = tok.convert_ids_to_tokens(list(range(n)))
        vocab, scores = resolve_hf_vocab(strings)
        bos_id = tok.bos_token_id
        if tok.eos_token_id is not None:
            eos_ids = [tok.eos_token_id]
    elif cls == "LlamaTokenizer":
        vocab, scores, bos_id, eos_id = resolve_sentencepiece_vocab(
            source_dir / "tokenizer.model")
        eos_ids = [eos_id]
    else:
        raise ValueError(f"tokenizer class {cls} is not supported")

    if bos_id is None or eos_ids is None:
        config = _open_json(source_dir / "config.json")
        if bos_id is None:
            bos_id = config["bos_token_id"]
        if eos_ids is None:
            eos = config["eos_token_id"]
            eos_ids = list(eos) if isinstance(eos, list) else [eos]

    chat_template = tok_config.get("chat_template")
    add_bos = bool(tok_config.get("add_bos_token", True))

    data = TokenizerData(vocab=vocab, scores=scores, bos_id=int(bos_id),
                         add_bos=add_bos, eos_token_ids=[int(e) for e in eos_ids],
                         chat_template=chat_template,
                         max_token_length=max(len(t) for t in vocab))
    write_tfile(output_path, data)
    if progress:
        print(f"✅ wrote {output_path}: vocab={len(vocab)} bos={bos_id} "
              f"eos={eos_ids} add_bos={add_bos}")
    return str(output_path)


# ---------------------------------------------------------------------------
# Llama 2 sentencepiece → .t
# ---------------------------------------------------------------------------

# reference: convert-tokenizer-llama2.py:6
LLAMA2_CHAT_TEMPLATE = (
    "{% if messages[0]['role'] == 'system' %}{% set loop_messages = messages[1:] %}"
    "{% set system_message = messages[0]['content'] %}{% else %}"
    "{% set loop_messages = messages %}{% set system_message = false %}{% endif %}"
    "{% for message in loop_messages %}"
    "{% if (message['role'] == 'user') != (loop.index0 % 2 == 0) %}"
    "{{ raise_exception('Conversation roles must alternate user/assistant/user/assistant/...') }}"
    "{% endif %}"
    "{% if loop.index0 == 0 and system_message != false %}"
    "{% set content = '<<SYS>>\\n' + system_message + '\\n<</SYS>>\\n\\n' + message['content'] %}"
    "{% else %}{% set content = message['content'] %}{% endif %}"
    "{% if message['role'] == 'user' %}"
    "{{ bos_token + '[INST] ' + content.strip() + ' [/INST]' }}"
    "{% elif message['role'] == 'assistant' %}"
    "{{ ' '  + content.strip() + ' ' + eos_token }}{% endif %}{% endfor %}")


def convert_tokenizer_llama2(source_dir: str | Path, output_path: str | Path,
                             *, progress: bool = True) -> str:
    """Llama 2 sentencepiece tokenizer.model → .t
    (reference: convert-tokenizer-llama2.py)."""
    vocab, scores, bos_id, eos_id = resolve_sentencepiece_vocab(
        Path(source_dir) / "tokenizer.model")
    data = TokenizerData(vocab=vocab, scores=scores, bos_id=bos_id,
                         add_bos=True, eos_token_ids=[eos_id],
                         chat_template=LLAMA2_CHAT_TEMPLATE,
                         max_token_length=max(len(t) for t in vocab))
    write_tfile(output_path, data)
    if progress:
        print(f"✅ wrote {output_path}: vocab={len(vocab)}")
    return str(output_path)


# ---------------------------------------------------------------------------
# Llama 3 tiktoken model file → .t
# ---------------------------------------------------------------------------

LLAMA3_N_SPECIAL_TOKENS = 256
LLAMA3_BOS_ID = 128000
LLAMA3_EOS_ID = 128001
LLAMA3_CHAT_EOS_ID = 128009

# reference: convert-tokenizer-llama3.py:32
LLAMA3_CHAT_TEMPLATE = (
    "{% set loop_messages = messages %}{% for message in loop_messages %}"
    "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
    "+ message['content'] | trim + '<|eot_id|>' %}"
    "{% if loop.index0 == 0 %}{% set content = bos_token + content %}{% endif %}"
    "{{ content }}{% endfor %}{% if add_generation_prompt %}"
    "{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}{% endif %}")


def llama3_special_tokens() -> list[str]:
    """The Llama 3 reserved special-token id block
    (reference: convert-tokenizer-llama3.py:13-28)."""
    named = ["<|begin_of_text|>", "<|end_of_text|>",
             "<|reserved_special_token_0|>", "<|reserved_special_token_1|>",
             "<|reserved_special_token_2|>", "<|reserved_special_token_3|>",
             "<|start_header_id|>", "<|end_header_id|>",
             "<|reserved_special_token_4|>", "<|eot_id|>"]
    reserved = [f"<|reserved_special_token_{i}|>"
                for i in range(5, LLAMA3_N_SPECIAL_TOKENS - 5)]
    return named + reserved


def convert_tokenizer_llama3(model_path: str | Path, output_path: str | Path,
                             *, progress: bool = True) -> str:
    """Llama 3 tiktoken ``tokenizer.model`` (``<base64> <rank>`` lines) → .t
    (reference: convert-tokenizer-llama3.py). Parses the file directly —
    tiktoken itself is not required."""
    vocab: list[bytes] = []
    scores: list[float] = []
    with open(model_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            b64, rank = line.split(" ")
            vocab.append(base64.b64decode(b64))
            scores.append(-float(rank))

    next_id = len(vocab)
    for i, token in enumerate(llama3_special_tokens()):
        vocab.append(token.encode("utf-8"))
        scores.append(-float(next_id + i))

    data = TokenizerData(vocab=vocab, scores=scores, bos_id=LLAMA3_BOS_ID,
                         add_bos=True,
                         eos_token_ids=[LLAMA3_EOS_ID, LLAMA3_CHAT_EOS_ID],
                         chat_template=LLAMA3_CHAT_TEMPLATE,
                         max_token_length=max(len(t) for t in vocab))
    write_tfile(output_path, data)
    if progress:
        print(f"✅ wrote {output_path}: vocab={len(vocab)}")
    return str(output_path)
