"""Offline converter tooling — HF/Meta checkpoints → .m, tokenizers → .t.

The TPU-side equivalent of the reference's converter/ directory
(reference: converter/convert-hf.py, convert-llama.py, convert-tokenizer-*.py,
writer.py, tokenizer-writer.py). Output files are wire-compatible with the
reference formats so models prepared for either runtime are interchangeable.

Usage (CLI):

    python -m dllama_tpu.convert hf <hf_model_dir> q40 <name>
    python -m dllama_tpu.convert llama <meta_model_dir> q40
    python -m dllama_tpu.convert tokenizer-hf <hf_model_dir> <name>
    python -m dllama_tpu.convert tokenizer-llama2 <dir_with_tokenizer.model>
    python -m dllama_tpu.convert tokenizer-llama3 <tokenizer.model>
"""

from .hf import convert_hf, load_hf_config
from .tokenizers import (
    convert_tokenizer_hf,
    convert_tokenizer_llama2,
    convert_tokenizer_llama3,
)

__all__ = [
    "convert_hf",
    "load_hf_config",
    "convert_tokenizer_hf",
    "convert_tokenizer_llama2",
    "convert_tokenizer_llama3",
]
