// Native Q40/Q80 block codecs + TPU-layout repack — the host-side runtime's
// hot data path (the TPU-native equivalent of the reference's C++ quant layer,
// reference: src/nn/nn-quants.cpp:67-240, and of its weight-shard loader,
// src/nn/nn-network.cpp:809-854: here the "loader" is mmap → repack to K-major
// planes → jax.device_put, and this file is the repack).
//
// Semantics are byte-identical to the numpy codecs in
// dllama_tpu/formats/quants.py (which follow the reference converter,
// converter/writer.py:29-74):
//   Q40: 32-elem block = f16 scale d (signed absmax / -8) + 16 nibble bytes,
//        code = clip(floor(x/d + 8.5), 0, 15), value = (code - 8) * d.
//   Q80: 32-elem block = f16 scale d (absmax / 127) + 32 int8 codes,
//        code = rint(x/d) (round-half-even, matching np.round).
//
// f16 conversion uses _Float16 (IEEE binary16, round-to-nearest-even —
// matching numpy's astype(float16)). Threaded by block ranges, mirroring the
// reference's SPLIT_THREADS (src/nn/nn-quants.hpp:82-86).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kBlock = 32;
constexpr int64_t kQ40Bytes = 18;  // f16 + 16 nibble bytes
constexpr int64_t kQ80Bytes = 34;  // f16 + 32 int8

inline float f16_to_f32(const uint8_t* p) {
    _Float16 h;
    std::memcpy(&h, p, sizeof(h));
    return (float)h;
}

inline void f32_to_f16(float x, uint8_t* p) {
    _Float16 h = (_Float16)x;
    std::memcpy(p, &h, sizeof(h));
}

// run fn(first_block, last_block) over nthreads ranges
template <typename F>
void split_blocks(int64_t n_blocks, int nthreads, F fn) {
    if (nthreads <= 1 || n_blocks < 2 * nthreads) {
        fn(0, n_blocks);
        return;
    }
    std::vector<std::thread> ts;
    int64_t per = (n_blocks + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; t++) {
        int64_t a = t * per;
        int64_t b = a + per < n_blocks ? a + per : n_blocks;
        if (a >= b) break;
        ts.emplace_back([=] { fn(a, b); });
    }
    for (auto& t : ts) t.join();
}

void q40_quantize_range(const float* x, uint8_t* out, int64_t b0, int64_t b1) {
    for (int64_t b = b0; b < b1; b++) {
        const float* g = x + b * kBlock;
        uint8_t* o = out + b * kQ40Bytes;
        float gmax = g[0], gmin = g[0];
        for (int i = 1; i < kBlock; i++) {
            if (g[i] > gmax) gmax = g[i];
            if (g[i] < gmin) gmin = g[i];
        }
        float d = ((-gmin > gmax) ? gmin : gmax) / -8.0f;
        f32_to_f16(d, o);
        float inv = d != 0.0f ? 1.0f / d : 0.0f;
        for (int j = 0; j < kBlock / 2; j++) {
            float q0 = std::floor(g[j] * inv + 8.5f);
            float q1 = std::floor(g[j + kBlock / 2] * inv + 8.5f);
            uint8_t c0 = (uint8_t)(q0 < 0 ? 0 : (q0 > 15 ? 15 : q0));
            uint8_t c1 = (uint8_t)(q1 < 0 ? 0 : (q1 > 15 ? 15 : q1));
            o[2 + j] = (uint8_t)((c0 & 0xF) | ((c1 & 0xF) << 4));
        }
    }
}

void q40_dequantize_range(const uint8_t* in, float* out, int64_t b0, int64_t b1) {
    for (int64_t b = b0; b < b1; b++) {
        const uint8_t* p = in + b * kQ40Bytes;
        float* o = out + b * kBlock;
        float d = f16_to_f32(p);
        for (int j = 0; j < kBlock / 2; j++) {
            uint8_t byte = p[2 + j];
            o[j] = (float)((int)(byte & 0x0F) - 8) * d;
            o[j + kBlock / 2] = (float)((int)(byte >> 4) - 8) * d;
        }
    }
}

void q80_quantize_range(const float* x, uint8_t* out, int64_t b0, int64_t b1) {
    for (int64_t b = b0; b < b1; b++) {
        const float* g = x + b * kBlock;
        uint8_t* o = out + b * kQ80Bytes;
        float amax = 0.0f;
        for (int i = 0; i < kBlock; i++) {
            float a = std::fabs(g[i]);
            if (a > amax) amax = a;
        }
        float d = amax / 127.0f;
        f32_to_f16(d, o);
        float inv = d != 0.0f ? 1.0f / d : 0.0f;
        int8_t* q = (int8_t*)(o + 2);
        for (int i = 0; i < kBlock; i++) {
            // rintf under the default FE_TONEAREST = round-half-even (np.round)
            q[i] = (int8_t)std::rint(g[i] * inv);
        }
    }
}

void q80_dequantize_range(const uint8_t* in, float* out, int64_t b0, int64_t b1) {
    for (int64_t b = b0; b < b1; b++) {
        const uint8_t* p = in + b * kQ80Bytes;
        float* o = out + b * kBlock;
        float d = f16_to_f32(p);
        const int8_t* q = (const int8_t*)(p + 2);
        for (int i = 0; i < kBlock; i++) o[i] = (float)q[i] * d;
    }
}

}  // namespace

extern "C" {

// n = element count (multiple of 32); layouts are the wire formats above.
void q40_quantize(const float* x, int64_t n, uint8_t* out, int nthreads) {
    split_blocks(n / kBlock, nthreads, [&](int64_t a, int64_t b) {
        q40_quantize_range(x, out, a, b);
    });
}

void q40_dequantize(const uint8_t* in, int64_t n, float* out, int nthreads) {
    split_blocks(n / kBlock, nthreads, [&](int64_t a, int64_t b) {
        q40_dequantize_range(in, out, a, b);
    });
}

void q80_quantize(const float* x, int64_t n, uint8_t* out, int nthreads) {
    split_blocks(n / kBlock, nthreads, [&](int64_t a, int64_t b) {
        q80_quantize_range(x, out, a, b);
    });
}

void q80_dequantize(const uint8_t* in, int64_t n, float* out, int nthreads) {
    split_blocks(n / kBlock, nthreads, [&](int64_t a, int64_t b) {
        q80_dequantize_range(in, out, a, b);
    });
}

// Fused unpack + transpose + f16→f32 of a Q40 matmul weight, disk row-major
// [rows, cols] → device K-major planes: scales_f32 [cols/32, rows],
// codes_i8 [cols, rows] (centered, in [-8, 7]). One pass over the mmap'd
// bytes; this is the per-shard weight-load hot loop.
void q40_repack_kmajor(const uint8_t* in, int64_t rows, int64_t cols,
                       float* scales, int8_t* codes, int nthreads) {
    const int64_t blocks_per_row = cols / kBlock;
    // row-tiled transpose: within a tile the inner loop runs over rows so the
    // K-major stores are contiguous runs (the naive row-major walk scatters
    // every byte ~rows apart and is cache-bound)
    constexpr int64_t kTile = 128;
    const int64_t n_tiles = (rows + kTile - 1) / kTile;
    split_blocks(n_tiles, nthreads, [&](int64_t t0, int64_t t1) {
        for (int64_t t = t0; t < t1; t++) {
            const int64_t r0 = t * kTile;
            const int64_t r1 = (r0 + kTile < rows) ? r0 + kTile : rows;
            for (int64_t bc = 0; bc < blocks_per_row; bc++) {
                const int64_t c0 = bc * kBlock;
                float* srow = scales + bc * rows;
                for (int64_t r = r0; r < r1; r++)
                    srow[r] = f16_to_f32(in + (r * blocks_per_row + bc) * kQ40Bytes);
                for (int j = 0; j < kBlock / 2; j++) {
                    int8_t* lo = codes + (c0 + j) * rows;
                    int8_t* hi = codes + (c0 + j + kBlock / 2) * rows;
                    for (int64_t r = r0; r < r1; r++) {
                        uint8_t byte =
                            in[(r * blocks_per_row + bc) * kQ40Bytes + 2 + j];
                        lo[r] = (int8_t)((int)(byte & 0x0F) - 8);
                        hi[r] = (int8_t)((int)(byte >> 4) - 8);
                    }
                }
            }
        }
    });
}

}  // extern "C"
