"""Native (C++) host-runtime components, loaded via ctypes.

The TPU compute path is JAX/XLA/Pallas; the *host* runtime around it — the
quant codecs and the mmap→device weight repack (the data-loader hot loop) —
is native C++, like the reference's (src/nn/nn-quants.cpp, and the weight
slicing half of src/nn/nn-network.cpp:809-854). The library is built on first
use with ``make`` and falls back to the numpy implementations in
:mod:`dllama_tpu.formats.quants` when a toolchain isn't available, so the
package stays importable everywhere.

All entry points are ``extern "C"`` over raw buffers; this module wraps them
with numpy ctypes bindings. Use :func:`get_lib` (returns ``None`` when
unavailable) or the typed wrappers below.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

_DIR = Path(__file__).resolve().parent

_lib: ctypes.CDLL | None = None
_tried = False

_c_f32p = ctypes.POINTER(ctypes.c_float)
_c_u8p = ctypes.POINTER(ctypes.c_uint8)
_c_i8p = ctypes.POINTER(ctypes.c_int8)


def default_threads() -> int:
    env = os.environ.get("DLLAMA_NATIVE_THREADS")
    if env:
        return max(1, int(env))
    return max(1, os.cpu_count() or 1)


def _host_signature() -> str:
    """Identity of the CPU the .so was built for: -march=native code moved to
    a different host (shared FS, container image reuse) can SIGILL the whole
    process, which ctypes cannot catch (advisor round-1 finding). The
    signature is EMBEDDED IN THE .so FILENAME, so check-and-load is atomic:
    a foreign host's build has a different name and is simply never opened —
    no tag file to race, no rebuild ping-pong invalidating other hosts'
    builds on a shared FS."""
    import hashlib
    import platform

    parts = [platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    parts.append(line.strip())
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def _so_path() -> Path:
    return _DIR / f"libdllama_native.{_host_signature()}.so"


def _stale() -> bool:
    so = _so_path()
    if not so.exists():
        return True
    try:
        return any(src.stat().st_mtime > so.stat().st_mtime
                   for src in _DIR.glob("*.cpp"))
    except OSError:
        return True


def _build() -> bool:
    """Build to a per-(host, process) temp name and rename into place:
    concurrent first-use builds (pytest workers, multi-process launches) each
    produce a valid .so and the atomic replace keeps the last one. The host
    signature in the temp name keeps two hosts with colliding pids (pid
    namespaces on a shared volume) from interleaving builds and renaming a
    foreign binary under this host's signed name."""
    tmp = f"libdllama_native.so.tmp.{_host_signature()}.{os.getpid()}"
    try:
        proc = subprocess.run(
            ["make", "-C", str(_DIR), "-s", f"SO={tmp}"],
            capture_output=True, text=True, timeout=120)
        if proc.returncode != 0 or not (_DIR / tmp).exists():
            return False
        os.replace(_DIR / tmp, _so_path())
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        (_DIR / tmp).unlink(missing_ok=True)


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, (re)building it on first call when missing
    or older than its source; None if that fails. Only ever dlopens a .so
    whose filename carries THIS host's CPU signature — a build from another
    machine (shared FS) is invisible rather than a SIGILL risk."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("DLLAMA_NO_NATIVE"):
        return None
    if _stale() and not _build() and not _so_path().exists():
        return None
    try:
        lib = ctypes.CDLL(str(_so_path()))
    except OSError:
        return None
    for name, (argtypes, restype) in {
        "q40_quantize": ((_c_f32p, ctypes.c_int64, _c_u8p, ctypes.c_int), None),
        "q40_dequantize": ((_c_u8p, ctypes.c_int64, _c_f32p, ctypes.c_int), None),
        "q80_quantize": ((_c_f32p, ctypes.c_int64, _c_u8p, ctypes.c_int), None),
        "q80_dequantize": ((_c_u8p, ctypes.c_int64, _c_f32p, ctypes.c_int), None),
        "q40_repack_kmajor": ((_c_u8p, ctypes.c_int64, ctypes.c_int64,
                               _c_f32p, _c_i8p, ctypes.c_int), None),
        "bpe_create": ((_c_u8p, ctypes.POINTER(ctypes.c_int64), _c_f32p,
                        ctypes.c_int32, ctypes.c_int32), ctypes.c_void_p),
        "bpe_destroy": ((ctypes.c_void_p,), None),
        "bpe_merge": ((ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
                       ctypes.c_int64), ctypes.c_int64),
    }.items():
        fn = getattr(lib, name)
        fn.argtypes = list(argtypes)
        fn.restype = restype
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def _u8(buf) -> np.ndarray:
    a = np.frombuffer(buf, dtype=np.uint8) if not isinstance(buf, np.ndarray) else buf
    return np.ascontiguousarray(a.reshape(-1).view(np.uint8))


def q40_quantize(x: np.ndarray, nthreads: int | None = None) -> bytes | None:
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    out = np.empty((x.size // 32) * 18, dtype=np.uint8)
    lib.q40_quantize(x.ctypes.data_as(_c_f32p), x.size,
                     out.ctypes.data_as(_c_u8p), nthreads or default_threads())
    return out.tobytes()


def q40_dequantize(buf, n: int, nthreads: int | None = None) -> np.ndarray | None:
    lib = get_lib()
    if lib is None:
        return None
    raw = _u8(buf)
    need = (n // 32) * 18
    if raw.size < need:
        raise ValueError(f"q40 buffer too small: {raw.size} < {need} bytes for n={n}")
    out = np.empty(n, dtype=np.float32)
    lib.q40_dequantize(raw.ctypes.data_as(_c_u8p), n,
                       out.ctypes.data_as(_c_f32p), nthreads or default_threads())
    return out


def q80_quantize(x: np.ndarray, nthreads: int | None = None) -> bytes | None:
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    out = np.empty((x.size // 32) * 34, dtype=np.uint8)
    lib.q80_quantize(x.ctypes.data_as(_c_f32p), x.size,
                     out.ctypes.data_as(_c_u8p), nthreads or default_threads())
    return out.tobytes()


def q80_dequantize(buf, n: int, nthreads: int | None = None) -> np.ndarray | None:
    lib = get_lib()
    if lib is None:
        return None
    raw = _u8(buf)
    need = (n // 32) * 34
    if raw.size < need:
        raise ValueError(f"q80 buffer too small: {raw.size} < {need} bytes for n={n}")
    out = np.empty(n, dtype=np.float32)
    lib.q80_dequantize(raw.ctypes.data_as(_c_u8p), n,
                       out.ctypes.data_as(_c_f32p), nthreads or default_threads())
    return out


def q40_repack_kmajor(buf, rows: int, cols: int, nthreads: int | None = None
                      ) -> tuple[np.ndarray, np.ndarray] | None:
    """Disk row-major Q40 [rows, cols] → K-major device planes
    (scales_f32 [cols/32, rows], codes_i8 [cols, rows])."""
    lib = get_lib()
    if lib is None:
        return None
    raw = _u8(buf)
    assert raw.size == rows * (cols // 32) * 18, (raw.size, rows, cols)
    scales = np.empty((cols // 32, rows), dtype=np.float32)
    codes = np.empty((cols, rows), dtype=np.int8)
    lib.q40_repack_kmajor(raw.ctypes.data_as(_c_u8p), rows, cols,
                          scales.ctypes.data_as(_c_f32p),
                          codes.ctypes.data_as(_c_i8p),
                          nthreads or default_threads())
    return scales, codes


class BpeMerger:
    """Handle-holding wrapper over the native BPE merge engine
    (tokenizer.cpp): builds the vocab hash map once, then ``merge`` runs
    allocation-light per call. Construct via :func:`bpe_merger` (None when
    the library is unavailable or handle creation fails)."""

    def __init__(self, lib: ctypes.CDLL, handle: int):
        self._lib = lib
        self._h = handle

    def merge(self, tokens: list[int]) -> list[int] | None:
        """Greedy-merge ``tokens`` (same output as bpe.Tokenizer._merge);
        None signals the caller to fall back (bad ids, dead handle)."""
        if self._h is None:
            return None
        n = len(tokens)
        if n < 2:
            return list(tokens)
        arr = np.asarray(tokens, dtype=np.int32)
        out_n = self._lib.bpe_merge(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
        if out_n < 0:
            return None
        return arr[:out_n].tolist()

    def __del__(self):  # noqa: D105 — process-exit teardown may be partial
        try:
            if self._h is not None:
                self._lib.bpe_destroy(self._h)
                self._h = None
        except Exception:  # pragma: no cover — interpreter shutdown
            pass


def bpe_merger(vocab: list[bytes], scores, n_regular: int) -> "BpeMerger | None":
    """Build a native merge engine from the tokenizer tables, or None."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(vocab)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(p) for p in vocab], out=offsets[1:])
    blob = np.frombuffer(b"".join(vocab), dtype=np.uint8) if offsets[n] \
        else np.empty(0, dtype=np.uint8)
    sc = np.ascontiguousarray(scores, dtype=np.float32)
    if sc.size != n:
        return None
    h = lib.bpe_create(blob.ctypes.data_as(_c_u8p),
                       offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                       sc.ctypes.data_as(_c_f32p), n, n_regular)
    if not h:
        return None
    return BpeMerger(lib, h)
