// ThreadSanitizer stress binary for the native host-runtime library — the
// race-detection tier SURVEY.md §5 lists as "partial" (the reference ships
// no sanitizer tier at all; its threaded kernels rely on review).
//
// Built by `make -C dllama_tpu/native tsan` (tests/test_native.py builds and
// runs it): links quants.cpp + tokenizer.cpp with -fsanitize=thread and
// drives every threaded entry point the way the loader / tokenizer do —
// internal block-range pools at nthreads=4 PLUS concurrent outer callers on
// disjoint buffers (the library's documented concurrency contract: calls
// share no state except the read-only inputs; BPE handles are per-caller).
// Any data race TSAN finds fails the run (TSAN_OPTIONS=halt_on_error=1).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void q40_quantize(const float* x, int64_t n, uint8_t* out, int nthreads);
void q40_dequantize(const uint8_t* in, int64_t n, float* out, int nthreads);
void q80_quantize(const float* x, int64_t n, uint8_t* out, int nthreads);
void q80_dequantize(const uint8_t* in, int64_t n, float* out, int nthreads);
void q40_repack_kmajor(const uint8_t* in, int64_t rows, int64_t cols,
                       float* scales, int8_t* codes, int nthreads);
void* bpe_create(const uint8_t* vocab_bytes, const int64_t* offsets,
                 const float* scores, int32_t n_vocab, int32_t max_len);
void bpe_destroy(void* handle);
int64_t bpe_merge(void* handle, int32_t* tokens, int64_t n);
}

namespace {

constexpr int64_t kN = 32 * 2048;   // elements per worker (64 KiB of codes)
constexpr int64_t kRows = 32, kCols = 2048;

void quant_worker(unsigned seed) {
  std::vector<float> x(kN);
  for (int64_t i = 0; i < kN; ++i) {
    seed = seed * 1664525u + 1013904223u;
    x[i] = static_cast<float>(static_cast<int32_t>(seed >> 8)) * 1e-7f;
  }
  std::vector<uint8_t> q40(kN / 32 * 18), q80(kN / 32 * 34);
  std::vector<float> back(kN);
  // inner pools (nthreads=4) are the race surface: block-range splits over
  // shared input/output spans
  q40_quantize(x.data(), kN, q40.data(), 4);
  q40_dequantize(q40.data(), kN, back.data(), 4);
  q80_quantize(x.data(), kN, q80.data(), 4);
  q80_dequantize(q80.data(), kN, back.data(), 4);
  static_assert(kRows * kCols == kN, "repack reuses the same buffer");
  std::vector<float> scales(kCols / 32 * kRows);
  std::vector<int8_t> codes(kCols * kRows);
  q40_repack_kmajor(q40.data(), kRows, kCols, scales.data(), codes.data(), 4);
}

void bpe_worker() {
  // tiny byte vocab + a few merges, one handle per caller (the contract)
  std::vector<uint8_t> vocab_bytes;
  std::vector<int64_t> offsets;
  std::vector<float> scores;
  for (int b = 0; b < 256; ++b) {
    offsets.push_back(static_cast<int64_t>(vocab_bytes.size()));
    vocab_bytes.push_back(static_cast<uint8_t>(b));
    scores.push_back(0.0f);
  }
  const char* merges[] = {"ab", "bc", "abc"};
  for (int i = 0; i < 3; ++i) {
    offsets.push_back(static_cast<int64_t>(vocab_bytes.size()));
    vocab_bytes.insert(vocab_bytes.end(), merges[i],
                       merges[i] + std::strlen(merges[i]));
    scores.push_back(static_cast<float>(i + 1));
  }
  offsets.push_back(static_cast<int64_t>(vocab_bytes.size()));
  // every token is lookup-eligible (n_regular == n): the merge tokens must
  // participate or the heap/merge machinery never runs and the tier only
  // exercises the validation loop
  const auto n_vocab = static_cast<int32_t>(scores.size());
  void* h = bpe_create(vocab_bytes.data(), offsets.data(), scores.data(),
                       n_vocab, n_vocab);
  if (!h) { std::fprintf(stderr, "bpe_create failed\n"); return; }
  int64_t merged = -1;
  for (int round = 0; round < 50; ++round) {
    int32_t toks[] = {'a', 'b', 'c', 'a', 'b', 'x', 'b', 'c'};
    merged = bpe_merge(h, toks, 8);
  }
  bpe_destroy(h);
  if (merged != 4) {  // abc, ab, x, bc — the heap genuinely merged
    std::fprintf(stderr, "bpe merge inert: got %lld\n",
                 static_cast<long long>(merged));
  }
}

}  // namespace

int main() {
  std::vector<std::thread> ts;
  for (unsigned i = 0; i < 4; ++i) ts.emplace_back(quant_worker, 7u + i);
  for (int i = 0; i < 2; ++i) ts.emplace_back(bpe_worker);
  for (auto& t : ts) t.join();
  std::puts("tsan stress ok");
  return 0;
}
