// Native BPE merge engine — the hot half of Tokenizer.encode.
//
// The reference's tokenizer is C++ (src/tokenizer.cpp:309-388: rescan-per-
// merge over a bsearch'd sorted vocab, O(n²)); ours keeps the same greedy
// policy — highest score wins, leftmost on ties — on a lazy-deletion heap
// over a doubly-linked token list, exactly mirroring the Python fallback in
// dllama_tpu/tokenizer/bpe.py::_merge (same entry ordering, so identical
// output by construction, proven by the equivalence suite in
// tests/test_tokenizer.py).
//
// C API: an opaque handle owns the regular-vocab hash map (bytes -> first
// id, matching the reference's stably-ordered unique-key bsearch) and the
// score table; merge calls then run allocation-light.

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct BpeHandle {
    // backing store for vocab bytes; string_view keys point into it
    std::string blob;
    std::vector<std::string_view> pieces;   // id -> bytes
    std::vector<float> scores;              // id -> merge score
    std::unordered_map<std::string_view, int32_t> lookup;  // bytes -> first id
};

struct HeapEntry {
    float neg_score;
    int64_t j;        // left node index
    int64_t ver_j;    // left node version at push time
    int64_t ver_k;    // right node version at push time
    int64_t k;        // right node index
    int32_t mid;      // merged token id
};

// Python's heapq pops the lexicographically SMALLEST tuple
// (-score, j, ver_j, ver_k, k, mid); priority_queue pops the LARGEST,
// so the comparator is "a after b" == "a > b" lexicographically.
struct EntryAfter {
    bool operator()(const HeapEntry& a, const HeapEntry& b) const {
        if (a.neg_score != b.neg_score) return a.neg_score > b.neg_score;
        if (a.j != b.j) return a.j > b.j;
        if (a.ver_j != b.ver_j) return a.ver_j > b.ver_j;
        if (a.ver_k != b.ver_k) return a.ver_k > b.ver_k;
        if (a.k != b.k) return a.k > b.k;
        return a.mid > b.mid;
    }
};

}  // namespace

extern "C" {

// vocab_bytes: concatenation of all n pieces; offsets: n+1 prefix offsets.
// n_regular of the n ids participate in lookup (specials excluded).
void* bpe_create(const uint8_t* vocab_bytes, const int64_t* offsets,
                 const float* scores, int32_t n, int32_t n_regular) {
    if (n <= 0 || n_regular < 0 || n_regular > n) return nullptr;
    auto* h = new (std::nothrow) BpeHandle;
    if (!h) return nullptr;
    h->blob.assign(reinterpret_cast<const char*>(vocab_bytes),
                   static_cast<size_t>(offsets[n]));
    h->pieces.reserve(n);
    h->scores.assign(scores, scores + n);
    for (int32_t i = 0; i < n; i++) {
        h->pieces.emplace_back(h->blob.data() + offsets[i],
                               static_cast<size_t>(offsets[i + 1] - offsets[i]));
    }
    h->lookup.reserve(static_cast<size_t>(n_regular) * 2);
    for (int32_t i = 0; i < n_regular; i++) {
        h->lookup.emplace(h->pieces[i], i);  // emplace keeps the FIRST id
    }
    return h;
}

void bpe_destroy(void* handle) {
    delete static_cast<BpeHandle*>(handle);
}

// In-place greedy merge of tokens[0..n); returns the merged length (<= n),
// or -1 on bad arguments. Token ids must be < vocab size.
int64_t bpe_merge(void* handle, int32_t* tokens, int64_t n) {
    auto* h = static_cast<BpeHandle*>(handle);
    if (!h || n < 0) return -1;
    if (n < 2) return n;
    const int64_t vocab_n = static_cast<int64_t>(h->pieces.size());
    for (int64_t i = 0; i < n; i++) {
        if (tokens[i] < 0 || tokens[i] >= vocab_n) return -1;
    }

    std::vector<int32_t> ids(tokens, tokens + n);
    std::vector<int64_t> prev(n), nxt(n), ver(n, 0);
    std::vector<uint8_t> alive(n, 1);
    for (int64_t i = 0; i < n; i++) {
        prev[i] = i - 1;
        nxt[i] = (i + 1 < n) ? i + 1 : -1;
    }

    std::priority_queue<HeapEntry, std::vector<HeapEntry>, EntryAfter> heap;
    std::string key;
    auto push = [&](int64_t j) {
        const int64_t k = nxt[j];
        if (k == -1) return;
        const std::string_view a = h->pieces[ids[j]], b = h->pieces[ids[k]];
        key.assign(a.data(), a.size());
        key.append(b.data(), b.size());
        auto it = h->lookup.find(std::string_view(key));
        if (it != h->lookup.end()) {
            heap.push({-h->scores[it->second], j, ver[j], ver[k], k,
                       it->second});
        }
    };

    for (int64_t j = 0; j + 1 < n; j++) push(j);
    while (!heap.empty()) {
        const HeapEntry e = heap.top();
        heap.pop();
        const int64_t j = e.j, k = e.k;
        if (!alive[j] || !alive[k] || ver[j] != e.ver_j || ver[k] != e.ver_k ||
            nxt[j] != k) {
            continue;  // stale: an endpoint merged since this pair was seen
        }
        ids[j] = e.mid;
        ver[j]++;
        alive[k] = 0;
        nxt[j] = nxt[k];
        if (nxt[k] != -1) prev[nxt[k]] = j;
        if (prev[j] != -1) push(prev[j]);
        push(j);
    }

    int64_t out = 0;
    for (int64_t j = 0; j != -1; j = nxt[j]) tokens[out++] = ids[j];
    return out;
}

}  // extern "C"
