"""dllama_tpu — a TPU-native distributed LLM inference framework.

A brand-new implementation of the capabilities of distributed-llama
(tensor-parallel Llama 2/3/3.x + Qwen3 inference with Q40 weights and quantized
activation exchange, CLI + OpenAI-compatible API), designed idiomatically for
TPU: JAX/XLA for the compute graph, `jax.sharding` meshes + XLA collectives for
the distribution layer, and Pallas kernels for the quantized hot ops.

Layer map (mirrors SURVEY.md §1 of the reference, re-architected for TPU):

    serve/     CLI (inference/chat/perplexity) + OpenAI-compatible HTTP API
    runtime/   InferenceEngine: jitted prefill/decode steps, KV cache, weights
    models/    functional transformer graphs (Llama, Qwen3), rope caches
    parallel/  mesh construction + shardings (TP/SP/DP) — replaces the
               reference's TCP mesh & sync steps with XLA collectives
    ops/       Pallas/XLA kernels: quantized matmul, attention, rmsnorm, sampling
    formats/   on-disk formats: .m model files, .t tokenizer files, Q40/Q80 codecs
    tokenizer/ BPE encode / streaming decode, sampler, chat templates, EOS
    convert/   HF safetensors → .m, HF/sentencepiece tokenizer → .t
    native/    C++ runtime components (weight repacker, tokenizer core)
"""

__version__ = "0.1.0"
