"""``python -m dllama_tpu`` — the dllama-equivalent CLI entry point."""

import sys

from .serve.cli import main

if __name__ == "__main__":
    sys.exit(main())
