"""Tokenizer layer: BPE encode, streaming decode, sampling, chat templates, EOS.

Behavior-compatible with the reference tokenizer stack
(reference: src/tokenizer.{hpp,cpp}); the on-disk .t format lives in
:mod:`dllama_tpu.formats.tfile`.
"""

from .bpe import Tokenizer  # noqa: F401
from .sampler import Sampler, xorshift_random_f32  # noqa: F401
from .chat import (  # noqa: F401
    ChatItem,
    ChatTemplateGenerator,
    ChatTemplateType,
    EosDetector,
    EosResult,
    GeneratedChat,
)
