"""Chat templating and streaming stop-sequence (EOS) detection.

Behavior-compatible with the reference (reference: src/tokenizer.cpp:517-722,
src/tokenizer.hpp:100-155): template type is auto-detected from the tokenizer's
stored jinja template string; rendering is hard-coded per family (llama2,
llama3, deepseek3, chatml); EosDetector is a streaming matcher that buffers
output while a stop string might be forming (MAYBE_EOS).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class ChatTemplateType(enum.Enum):
    UNKNOWN = "unknown"
    LLAMA2 = "llama2"
    LLAMA3 = "llama3"
    DEEP_SEEK3 = "deepSeek3"
    CHATML = "chatml"


@dataclass
class ChatItem:
    role: str
    message: str


@dataclass
class GeneratedChat:
    content: str
    public_prompt: str | None = None  # e.g. "<think>\n" surfaced to the user


class ChatTemplateGenerator:
    """Render a message list into a model prompt (tokenizer.cpp:547-635)."""

    def __init__(self, chat_template: str | None,
                 eos: str = "",
                 type: ChatTemplateType = ChatTemplateType.UNKNOWN):
        if type == ChatTemplateType.UNKNOWN:
            if chat_template is None:
                raise ValueError("the tokenizer does not include a chat template")
            if "[INST]" in chat_template:
                type = ChatTemplateType.LLAMA2
            elif "<|start_header_id|>" in chat_template:
                type = ChatTemplateType.LLAMA3
            elif "<｜Assistant｜>" in chat_template:
                type = ChatTemplateType.DEEP_SEEK3
            elif "<|im_start|>" in chat_template:
                type = ChatTemplateType.CHATML
            else:
                raise ValueError("not supported chat template")
        self.type = type
        self.eos = eos

    def generate(self, items: list[ChatItem],
                 append_generation_prompt: bool = True) -> GeneratedChat:
        buf: list[str] = []
        public_prompt = None
        t = self.type
        if t == ChatTemplateType.LLAMA2:
            i = 0
            if len(items) >= 2 and items[0].role == "system" and items[1].role == "user":
                buf.append("[INST] <<SYS>>\n" + items[0].message + "\n<</SYS>>\n\n"
                           + items[1].message + " [/INST]" + self.eos)
                i = 2
            for item in items[i:]:
                if item.role == "assistant":
                    buf.append(item.message + self.eos)
                elif item.role == "user":
                    buf.append("[INST] " + item.message + " [/INST]" + self.eos)
        elif t == ChatTemplateType.LLAMA3:
            for item in items:
                buf.append("<|start_header_id|>" + item.role + "<|end_header_id|>\n\n"
                           + item.message + self.eos)
            if append_generation_prompt:
                buf.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif t == ChatTemplateType.DEEP_SEEK3:
            i = 0
            if items and items[0].role == "system":
                buf.append(items[0].message)
                i = 1
            for item in items[i:]:
                if item.role == "user":
                    buf.append("<｜User｜>" + item.message)
                elif item.role == "assistant":
                    buf.append("<｜Assistant｜>" + item.message)
            if append_generation_prompt:
                buf.append("<｜Assistant｜><think>\n")
                public_prompt = "<think>\n"
        elif t == ChatTemplateType.CHATML:
            # Note: the reference appends the generation prompt inside the item
            # loop (tokenizer.cpp:617-629) which duplicates it per message; that
            # reads like a bug, so here it is appended once at the end.
            for item in items:
                if item.role in ("system", "user", "assistant"):
                    buf.append("<|im_start|>" + item.role + "\n" + item.message
                               + "<|im_end|>\n")
            if append_generation_prompt:
                buf.append("<|im_start|>assistant\n")
        else:
            raise ValueError(f"cannot render template {t}")
        return GeneratedChat(content="".join(buf), public_prompt=public_prompt)


class EosResult(enum.Enum):
    NOT_EOS = 0
    EOS = 1
    MAYBE_EOS = 2


class EosDetector:
    """Streaming stop-string detector with MAYBE_EOS buffering
    (tokenizer.cpp:637-722).

    ``padding_left``/``padding_right`` allow a stop string to be found embedded
    up to that many characters from the buffer edges (the CLI passes the max
    stop length for both — dllama.cpp:180).
    """

    def __init__(self, stop_token_ids: list[int], stop_pieces: list[str],
                 padding_left: int = 0, padding_right: int = 0):
        self.stop_token_ids = list(stop_token_ids)
        self.pieces = [p.encode("utf-8") for p in stop_pieces]
        self.padding_left = padding_left
        self.padding_right = padding_right
        self._buffer = bytearray()
        self._eos_pos: int | None = None

    def is_eos_token(self, token_id: int) -> bool:
        return token_id in self.stop_token_ids

    def append(self, token_id: int, piece: str | None) -> EosResult:
        if piece is not None:
            self._buffer.extend(piece.encode("utf-8"))

        if self.is_eos_token(token_id):
            self._eos_pos = len(self._buffer)
            return EosResult.EOS
        self._eos_pos = None

        buf = self._buffer
        for stop in self.pieces:
            if len(buf) > len(stop) + self.padding_left + self.padding_right:
                continue
            for lo in range(self.padding_left + 1):
                n = len(buf) - lo
                if n == 0 or n > len(stop) + self.padding_right:
                    continue
                n = min(n, len(stop))
                if buf[lo:lo + n] == stop[:n]:
                    if n == len(stop):
                        self._eos_pos = lo
                        del self._buffer[lo:]
                        return EosResult.EOS
                    return EosResult.MAYBE_EOS
        return EosResult.NOT_EOS

    def get_delta(self) -> str | None:
        """The text safe to flush to the user after the last append."""
        if not self._buffer:
            return None
        if self._eos_pos == 0:
            return None
        return bytes(self._buffer).decode("utf-8", errors="replace")

    def reset(self) -> None:
        self._buffer.clear()
        self._eos_pos = None
