"""Token sampler: greedy argmax, temperature softmax, top-p (nucleus).

Behavior-compatible with the reference sampler (reference:
src/tokenizer.cpp:389-510), including the xorshift* RNG so fixed-seed runs are
reproducible against the reference (tokenizer.cpp:25-36). This host-side numpy
sampler is the semantics oracle: the engine's decode loop normally uses the
fused on-device sampler (:mod:`dllama_tpu.ops.sampling`, dispatched by
``InferenceEngine.next_token``), and ``tests/test_sampling.py`` holds the two
to exact agreement over the oracle's RNG stream.
"""

from __future__ import annotations

import numpy as np

_MASK64 = (1 << 64) - 1


def xorshift_random_u32(state: int) -> tuple[int, int]:
    """xorshift* step (reference: tokenizer.cpp:25-31). Returns (u32, new_state)."""
    state &= _MASK64
    state ^= state >> 12
    state ^= (state << 25) & _MASK64
    state ^= state >> 27
    return ((state * 0x2545F4914F6CDD1D) & _MASK64) >> 32, state


def xorshift_random_f32(state: int) -> tuple[float, int]:
    """Random float32 in [0, 1) (reference: tokenizer.cpp:33-36)."""
    u, state = xorshift_random_u32(state)
    return (u >> 8) / 16777216.0, state


def softmax(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.float32)
    e = np.exp(x - x.max())
    return e / e.sum()


def sample_topp(probs: np.ndarray, topp: float, coin: float) -> int:
    """Nucleus sampling (reference: tokenizer.cpp:424-465).

    Reproduces the reference's cutoff pre-filter and its renormalization by the
    truncated cumulative mass (``coin * cumulative_prob``).
    """
    n = probs.shape[0]
    cutoff = (1.0 - topp) / (n - 1)
    idx = np.nonzero(probs >= cutoff)[0]
    # Descending sort; numpy's stable mergesort on -probs preserves index order
    # for ties like the reference's qsort comparator returning 0.
    order = idx[np.argsort(-probs[idx], kind="stable")]
    p = probs[order]
    csum = np.cumsum(p)
    over = np.nonzero(csum > topp)[0]
    last = int(over[0]) if over.size else p.shape[0] - 1
    cumulative = float(csum[last])
    r = coin * cumulative
    inner = np.nonzero(np.cumsum(p[:last + 1]) > r)[0]
    pick = int(inner[0]) if inner.size else last
    return int(order[pick])


def sample_mult(probs: np.ndarray, coin: float) -> int:
    """Multinomial via CDF scan (reference: tokenizer.cpp:403-414)."""
    cdf = np.cumsum(probs)
    hit = np.nonzero(coin < cdf)[0]
    return int(hit[0]) if hit.size else probs.shape[0] - 1


class Sampler:
    """Stateful sampler with the reference's CLI semantics."""

    def __init__(self, vocab_size: int, temperature: float, topp: float, seed: int):
        self.vocab_size = vocab_size
        self.temperature = temperature
        self.topp = topp
        self.rng_state = seed & _MASK64

    def set_temp(self, temperature: float) -> None:
        self.temperature = temperature

    def set_seed(self, seed: int) -> None:
        self.rng_state = seed & _MASK64

    def sample(self, logits: np.ndarray) -> int:
        logits = np.asarray(logits, dtype=np.float32)[: self.vocab_size]
        if self.temperature == 0.0:
            return int(np.argmax(logits))
        probs = softmax(logits / self.temperature)
        coin, self.rng_state = xorshift_random_f32(self.rng_state)
        if self.topp <= 0 or self.topp >= 1:
            return sample_mult(probs, coin)
        return sample_topp(probs, self.topp, coin)
