"""Greedy-merge BPE encoder and streaming UTF-8-safe decoder.

Behavior-compatible with the reference implementation
(reference: src/tokenizer.cpp:309-388 encode, 222-307 decode/detokUtf8,
194-208 token lookup). The vocab is raw bytes (byte-level BPE or
sentencepiece pieces produced by the converter); encoding works on bytes, so
multi-byte UTF-8 input accumulates until a vocab entry matches.

Differences from the reference, by design:

* lookup uses hash maps instead of ``bsearch`` over a sorted array;
* the merge loop keeps the reference's "highest score wins, leftmost on tie"
  policy but runs on a heap + doubly-linked list (O(n log n)) instead of the
  reference's rescan-per-merge (O(n²), tokenizer.cpp:349-377) — same output
  on every input, proven by tests/test_tokenizer.py's equivalence suite;
* unresolvable bytes raise ``ValueError`` instead of ``assert`` (the
  reference aborts — llm vocabularies always cover all bytes in practice).
"""

from __future__ import annotations

import heapq

from ..formats.tfile import TokenizerData, read_tfile

_REPLACEMENT = "�".encode("utf-8")  # 0xEF 0xBF 0xBD


def _utf8_expected_continuation(byte: int) -> int | None:
    """How many continuation bytes a UTF-8 lead byte announces; None if invalid."""
    if byte <= 0x7F:
        return 0
    if 0xC0 <= byte <= 0xDF:
        return 1
    if 0xE0 <= byte <= 0xEF:
        return 2
    if 0xF0 <= byte <= 0xF7:
        return 3
    return None


class Tokenizer:
    """Vocab + encode/decode over a parsed .t file."""

    def __init__(self, data: TokenizerData):
        self.data = data
        self.vocab = data.vocab
        self.scores = data.scores
        self.bos_id = data.bos_id
        self.add_bos = data.add_bos
        self.eos_token_ids = list(data.eos_token_ids)
        self.chat_template = data.chat_template
        self.vocab_size = data.vocab_size
        self.regular_vocab_size = data.regular_vocab_size

        # Regular vocab: bytes -> id. On duplicates keep the FIRST id, matching
        # the reference's bsearch over a stably-ordered array of unique keys.
        self._regular: dict[bytes, int] = {}
        for i in range(self.regular_vocab_size):
            self._regular.setdefault(self.vocab[i], i)
        # Special vocab keeps file order: the reference's prefix scan takes the
        # first match in vocab order (tokenizer.cpp:194-202).
        self._special: list[tuple[int, bytes]] = [
            (i, self.vocab[i])
            for i in range(self.regular_vocab_size, self.vocab_size)
        ]
        self._pending = bytearray()  # streaming decoder carry-over

    @classmethod
    def load(cls, path) -> "Tokenizer":
        return cls(read_tfile(path))

    def is_eos(self, token: int) -> bool:
        return token in self.eos_token_ids

    # -- encode -------------------------------------------------------------

    def encode(self, text: str | bytes, is_start: bool = True,
               add_special_tokens: bool = True) -> list[int]:
        """Tokenize: byte accumulation pass, then greedy best-score pair merging."""
        if isinstance(text, str):
            text = text.encode("utf-8")
        tokens: list[int] = []
        if is_start and self.add_bos and self.bos_id >= 0:
            tokens.append(self.bos_id)

        buf = bytearray()
        i = 0
        n = len(text)
        while i < n:
            if add_special_tokens:
                # The reference checks special tokens at every byte position,
                # even mid-accumulation (tokenizer.cpp:323-330).
                matched = next(((tid, len(piece)) for tid, piece in self._special
                                if text.startswith(piece, i)), None)
                if matched is not None:
                    if buf:
                        raise ValueError(
                            f"unresolvable bytes before special token: {bytes(buf)!r}")
                    tokens.append(matched[0])
                    i += matched[1]
                    continue
            buf.append(text[i])
            i += 1
            tid = self._regular.get(bytes(buf))
            if tid is not None:
                tokens.append(tid)
                buf.clear()
        if buf:
            raise ValueError(f"unresolvable bytes in input: {bytes(buf)!r}")

        return self._merge(tokens)

    def _native_merger(self):
        """Lazily-built native merge engine (native/tokenizer.cpp), or None.
        False caches 'tried and unavailable' so the fallback never re-probes."""
        m = self.__dict__.get("_bpe_native")
        if m is None:
            from .. import native

            m = (native.bpe_merger(self.vocab, self.scores,
                                   self.regular_vocab_size)
                 if native.available() else None) or False
            self._bpe_native = m
        return m or None

    def _merge(self, tokens: list[int]) -> list[int]:
        """Greedy merge: repeatedly merge the best-scoring adjacent pair,
        leftmost on ties — the reference's policy (tokenizer.cpp:349-377,
        strict ``>`` comparison ⇒ first max wins), on a lazy-deletion heap
        over a doubly-linked token list. A heap entry is
        ``(-score, left_pos, left_ver, right_ver, right_pos, merged_id)``;
        node versions invalidate entries whose endpoints merged since.

        The same algorithm also exists natively (native/tokenizer.cpp, the
        C++ twin of the reference's C++ encode) and is preferred when built;
        this Python path is the portable fallback and the equivalence oracle.
        """
        n = len(tokens)
        if n < 2:
            return tokens
        nat = self._native_merger()
        if nat is not None:
            out = nat.merge(tokens)
            if out is not None:
                return out
        ids = list(tokens)
        prev = list(range(-1, n - 1))
        nxt = list(range(1, n + 1))
        nxt[-1] = -1
        alive = [True] * n
        ver = [0] * n
        heap: list = []
        lookup = self._regular.get
        vocab, scores = self.vocab, self.scores

        def push(j: int) -> None:
            k = nxt[j]
            if k == -1:
                return
            mid = lookup(vocab[ids[j]] + vocab[ids[k]])
            if mid is not None:
                heapq.heappush(heap, (-scores[mid], j, ver[j], ver[k], k, mid))

        for j in range(n - 1):
            push(j)
        while heap:
            _, j, vj, vk, k, mid = heapq.heappop(heap)
            if (not alive[j] or not alive[k] or ver[j] != vj or ver[k] != vk
                    or nxt[j] != k):
                continue  # stale: an endpoint merged since this pair was seen
            ids[j] = mid
            ver[j] += 1
            alive[k] = False
            nxt[j] = nxt[k]
            if nxt[k] != -1:
                prev[nxt[k]] = j
            if prev[j] != -1:
                push(prev[j])
            push(j)
        out: list[int] = []
        j = 0
        while j != -1:  # node 0 is always the surviving head
            out.append(ids[j])
            j = nxt[j]
        return out

    # -- streaming decode ---------------------------------------------------

    def reset_decoder(self) -> None:
        self._pending.clear()

    def decode(self, token: int) -> str | None:
        """Decode one token for streaming output.

        Returns the printable delta, or None when nothing is emittable yet
        (bos, incomplete UTF-8 sequence). Incomplete trailing sequences stay
        buffered for the next call; invalid bytes become U+FFFD with stream
        recovery (tokenizer.cpp:224-285).
        """
        if token == self.bos_id:
            return None
        if self.is_eos(token):
            if self._pending:
                out = bytes(self._pending).decode("utf-8", errors="replace")
                self._pending.clear()
                return out
            return None
        self._pending.extend(self.vocab[token])
        return self._drain_utf8()

    def decode_all(self, tokens: list[int]) -> str:
        """Non-streaming convenience: decode a whole sequence."""
        self.reset_decoder()
        parts = [p for p in (self.decode(t) for t in tokens) if p]
        if self._pending:
            parts.append(bytes(self._pending).decode("utf-8", errors="replace"))
            self._pending.clear()
        return "".join(parts)

    def _drain_utf8(self) -> str | None:
        """Emit the longest valid-or-recovered UTF-8 prefix, keep the rest."""
        src = bytes(self._pending)
        out = bytearray()
        checkpoint = 0  # bytes of `out` that end on a sequence boundary
        checkpoint_src = 0
        i = 0
        expect = 0
        while i < len(src):
            c = src[i]
            recovery = False
            if expect:
                if (c & 0xC0) == 0x80:
                    out.append(c)
                    i += 1
                    expect -= 1
                else:
                    recovery = True
            else:
                exp = _utf8_expected_continuation(c)
                if exp is None:
                    recovery = True
                else:
                    out.append(c)
                    i += 1
                    expect = exp
            if not recovery:
                if not expect:
                    checkpoint = len(out)
                    checkpoint_src = i
            else:
                if expect:
                    expect = 0
                else:
                    i += 1
                del out[checkpoint:]
                out.extend(_REPLACEMENT)
                checkpoint = len(out)
                checkpoint_src = i
        self._pending = bytearray(src[checkpoint_src:])
        if checkpoint > 0:
            # errors="replace": the structural scan above validates lead/
            # continuation SHAPE only — a length-complete sequence can still
            # be invalid UTF-8 (overlong like f0 88 8f 83, surrogates,
            # > U+10FFFF). Those become U+FFFD instead of crashing the
            # stream, consistent with the byte-level recovery path.
            return out[:checkpoint].decode("utf-8", errors="replace")
        return None
