"""Model zoo launcher — download prequantized models and generate run scripts.

The TPU build's equivalent of the reference's launcher (reference:
launch.py:17-68 model table, 77-112 download loop): same 10-model registry of
prequantized ``.m``/``.t`` artifacts on Hugging Face, but the download is
per-part with true byte-range resume (a killed download continues from the
exact byte via a ``Range`` header and ``.partNN`` files; the reference
restarts the failed part from its start), and the generated command runs the
TPU CLI (``python -m dllama_tpu``) instead of the C++ binary.

Usage::

    python -m dllama_tpu.zoo llama3_2_1b_instruct_q40 [-y] [--skip-run] [--skip-script]
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import shlex
import subprocess
import sys
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable, Iterator

CHUNK = 1 << 16
ATTEMPTS = 8


def part_suffixes(n: int) -> list[str]:
    """aa, ab, ... az, ba, ... — the split(1) suffixes the zoo files use."""
    return [chr(97 + i // 26) + chr(97 + i % 26) for i in range(n)]


@dataclass(frozen=True)
class ZooModel:
    name: str
    model_urls: tuple[str, ...]
    tokenizer_url: str
    buffer_type: str = "q80"  # activation-sync float type (all zoo models: q80)
    mode: str = "chat"
    extra_args: tuple[str, ...] = ("--max-seq-len", "4096")


def _hf(repo: str, file: str) -> str:
    return f"https://huggingface.co/{repo}/resolve/main/{file}?download=true"


def _multipart(repo: str, base: str, n: int, sep: str = "_") -> tuple[str, ...]:
    return tuple(_hf(repo, f"{base}{sep}{s}") for s in part_suffixes(n))


def _entry(repo: str, model_file, tokenizer_file: str, **kw) -> ZooModel:
    urls = (model_file if isinstance(model_file, tuple)
            else (_hf(repo, model_file),))
    return ZooModel(name="", model_urls=urls,
                    tokenizer_url=_hf(repo, tokenizer_file), **kw)


_RAW: dict[str, ZooModel] = {
    "llama3_1_8b_instruct_q40": _entry(
        "b4rtaz/Llama-3_1-8B-Q40-Instruct-Distributed-Llama",
        "dllama_model_llama3.1_instruct_q40.m", "dllama_tokenizer_llama_3_1.t"),
    "llama3_1_405b_instruct_q40": _entry(
        "b4rtaz/Llama-3_1-405B-Q40-Instruct-Distributed-Llama",
        _multipart("b4rtaz/Llama-3_1-405B-Q40-Instruct-Distributed-Llama",
                   "dllama_model_llama31_405b_q40", 56),
        "dllama_tokenizer_llama_3_1.t"),
    "llama3_2_1b_instruct_q40": _entry(
        "b4rtaz/Llama-3_2-1B-Q40-Instruct-Distributed-Llama",
        "dllama_model_llama3.2-1b-instruct_q40.m", "dllama_tokenizer_llama3_2.t"),
    "llama3_2_3b_instruct_q40": _entry(
        "b4rtaz/Llama-3_2-3B-Q40-Instruct-Distributed-Llama",
        "dllama_model_llama3.2-3b-instruct_q40.m", "dllama_tokenizer_llama3_2.t"),
    "llama3_3_70b_instruct_q40": _entry(
        "b4rtaz/Llama-3_3-70B-Q40-Instruct-Distributed-Llama",
        _multipart("b4rtaz/Llama-3_3-70B-Q40-Instruct-Distributed-Llama",
                   "dllama_model_llama-3.3-70b_q40", 11, sep=""),
        "dllama_tokenizer_llama-3.3-70b.t"),
    "deepseek_r1_distill_llama_8b_q40": _entry(
        "b4rtaz/DeepSeek-R1-Distill-Llama-8B-Distributed-Llama",
        "dllama_model_deepseek-r1-distill-llama-8b_q40.m",
        "dllama_tokenizer_deepseek-r1-distill-llama-8b.t"),
    "qwen3_0.6b_q40": _entry(
        "b4rtaz/Qwen3-0.6B-Q40-Distributed-Llama",
        "dllama_model_qwen3_0.6b_q40.m", "dllama_tokenizer_qwen3_0.6b.t"),
    "qwen3_1.7b_q40": _entry(
        "b4rtaz/Qwen3-1.7B-Q40-Distributed-Llama",
        "dllama_model_qwen3_1.7b_q40.m", "dllama_tokenizer_qwen3_1.7b.t"),
    "qwen3_8b_q40": _entry(
        "b4rtaz/Qwen3-8B-Q40-Distributed-Llama",
        "dllama_model_qwen3_8b_q40.m", "dllama_tokenizer_qwen3_8b.t"),
    "qwen3_14b_q40": _entry(
        "b4rtaz/Qwen3-14B-Q40-Distributed-Llama",
        _multipart("b4rtaz/Qwen3-14B-Q40-Distributed-Llama",
                   "dllama_model_qwen3_14b_q40", 2),
        "dllama_tokenizer_qwen3_14b.t"),
}

MODELS: dict[str, ZooModel] = {
    name: dataclasses.replace(m, name=name) for name, m in _RAW.items()
}


# ---------------------------------------------------------------------------
# Download with byte-range resume
# ---------------------------------------------------------------------------

# fetch(url, start_byte) -> iterator of byte chunks from that offset
Fetch = Callable[[str, int], Iterator[bytes]]

_sleep = time.sleep  # monkeypatched in tests


class RangeNotSatisfiable(Exception):
    """The server says the requested start offset is at/past end-of-file —
    the part on disk is already complete."""


class RangeIgnored(Exception):
    """The server returned 200 to a Range request: it will always send the
    whole file, so resuming is impossible — restart the part from byte 0
    instead of retrying the identical doomed request."""


def _urllib_fetch(url: str, start: int) -> Iterator[bytes]:
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    req = Request(url)
    if start > 0:
        req.add_header("Range", f"bytes={start}-")
    try:
        resp = urlopen(req, timeout=30)
    except HTTPError as e:
        if e.code == 416:  # Range Not Satisfiable: the part is fully on disk
            raise RangeNotSatisfiable(url) from e
        raise
    with resp:
        if start > 0 and resp.status != 206:
            raise RangeIgnored(f"status {resp.status} for bytes={start}-")
        while True:
            chunk = resp.read(CHUNK)
            if not chunk:
                return
            yield chunk


def _download_part(url: str, part_path: Path, fetch: Fetch,
                   log: Callable[[str], None]) -> None:
    """Download one URL to ``part_path``, resuming from its current size.

    A server that answers ranged requests with 200 is remembered for the
    whole part: every later attempt restarts from byte 0 directly instead of
    burning attempts on resume probes known to be doomed."""
    no_resume = False
    for attempt in range(ATTEMPTS):
        if no_resume:
            start = 0
        else:
            start = part_path.stat().st_size if part_path.exists() else 0
        try:
            with open(part_path, "wb" if start == 0 else "ab") as f:
                for chunk in fetch(url, start):
                    f.write(chunk)
            return
        except RangeNotSatisfiable:
            # resuming past EOF: this part finished in an earlier run
            return
        except RangeIgnored as e:
            # retrying the same Range request would fail identically
            # (advisor round-1 finding) — restart the part from byte 0
            log(f"server ignored Range resume ({e}); restarting part from 0")
            no_resume = True
        except Exception as e:  # noqa: BLE001 - any transport error retries
            log(f"retry {attempt + 1}/{ATTEMPTS} after error at "
                f"byte {start}: {e}")
            _sleep(min(attempt, 5))
    raise OSError(f"failed to download {url} after {ATTEMPTS} attempts")


def download_file(urls: Iterable[str], path: str | Path, fetch: Fetch | None = None,
                  log: Callable[[str], None] = print, force: bool = False) -> Path:
    """Download ``urls`` (multi-part pieces) into one file at ``path``.

    Each part goes to ``<path>.partNN`` with byte-range resume, then the
    parts are concatenated and removed. An existing final file is kept
    unless ``force``.
    """
    path = Path(path)
    if path.exists() and not force:
        log(f"{path.name} already present, skipping (use --force to re-download)")
        return path
    fetch = fetch or _urllib_fetch
    urls = list(urls)
    part_paths = [path.with_name(f"{path.name}.part{i:02d}")
                  for i in range(len(urls))]
    for url, pp in zip(urls, part_paths):
        log(f"downloading {url}" + (f" -> {pp.name}" if len(urls) > 1 else ""))
        _download_part(url, pp, fetch, log)
    tmp = path.with_name(path.name + ".assemble")
    with open(tmp, "wb") as out:
        for pp in part_paths:
            with open(pp, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    out.write(chunk)
    os.replace(tmp, path)
    for pp in part_paths:
        pp.unlink(missing_ok=True)
    return path


def download_model(name: str, models_dir: str | Path = "models",
                   fetch: Fetch | None = None, log: Callable[[str], None] = print,
                   force: bool = False) -> tuple[Path, Path]:
    model = MODELS[name]
    d = Path(models_dir) / name
    d.mkdir(parents=True, exist_ok=True)
    m = download_file(model.model_urls, d / f"dllama_model_{name}.m",
                      fetch=fetch, log=log, force=force)
    t = download_file([model.tokenizer_url], d / f"dllama_tokenizer_{name}.t",
                      fetch=fetch, log=log, force=force)
    return m, t


# ---------------------------------------------------------------------------
# Run command / script generation
# ---------------------------------------------------------------------------


def run_command(name: str, model_path: str | Path, tokenizer_path: str | Path) -> str:
    model = MODELS[name]
    if model.mode == "chat":
        cmd = [sys.executable or "python", "-m", "dllama_tpu", "chat"]
    else:
        cmd = [sys.executable or "python", "-m", "dllama_tpu", "inference",
               "--steps", "64", "--prompt", "Hello world"]
    cmd += ["--model", str(model_path), "--tokenizer", str(tokenizer_path),
            "--buffer-float-type", model.buffer_type]
    cmd += list(model.extra_args)
    return " ".join(shlex.quote(c) for c in cmd)


def write_run_script(name: str, command: str, directory: str | Path = ".") -> Path:
    p = Path(directory) / f"run_{name}.sh"
    p.write_text(f"#!/bin/sh\n\n{command}\n")
    p.chmod(0o755)
    return p


def usage() -> str:
    lines = [
        "Usage: python -m dllama_tpu.zoo <model> [options]",
        "",
        "Options:",
        "  --skip-run     do not run the model after download",
        "  --skip-script  do not create a run_<model>.sh script",
        "  --models-dir   download directory (default: models)",
        "  --force        re-download existing files",
        "  -y             skip confirmation prompts",
        "",
        "Available models:",
    ]
    lines += [f"  {n}" for n in MODELS]
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dllama_tpu.zoo", usage=usage(), add_help=True)
    parser.add_argument("model", nargs="?", default=None)
    parser.add_argument("--skip-run", action="store_true")
    parser.add_argument("--skip-script", action="store_true")
    parser.add_argument("--models-dir", default="models")
    parser.add_argument("--force", action="store_true")
    parser.add_argument("-y", dest="yes", action="store_true")
    args = parser.parse_args(argv)
    if args.model is None:
        print(usage())
        return 1
    name = args.model.replace("-", "_")
    if name not in MODELS:
        print(f"unknown model: {name}\n\n{usage()}")
        return 1

    mp, tp = download_model(name, models_dir=args.models_dir, force=args.force)
    cmd = run_command(name, mp, tp)
    print("\nTo run:\n")
    print(f"  {cmd}\n")
    if not args.skip_script:
        script = write_run_script(name, cmd)
        print(f"created {script}")
    if not args.skip_run:
        go = args.yes or input(
            "run now? [y/N] ").strip().lower() in ("y", "yes")
        if go:
            return subprocess.call(cmd, shell=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
