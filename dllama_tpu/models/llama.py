"""Functional transformer forward for Llama 2/3/3.x and Qwen3.

This replaces the reference's per-node op-graph builder (reference:
buildLlmNet, src/llm.cpp:142-490) with a single SPMD program: the graph that
the reference assembles as [merge_add, inv_rms, rms_norm, cast, matmul_q/k/v,
(qwen3 q/k norms), rope, shift, multihead_att, cast, matmul_wo, cast, SYNC] +
[merge_add, inv_rms, rms_norm, cast, w1/w3, silu, mul, cast, w2, cast, SYNC]
per layer (llm.cpp:226-443) is expressed directly in jnp; tensor-parallel
synchronization (the two all-reduces per layer) is carried by sharding
annotations + XLA collectives instead of explicit SYNC steps.

Design choices (TPU-first, not a translation):

* **Stacked layer parameters + ``lax.scan``** — one compiled layer body
  regardless of depth; keeps compile time O(1) in ``n_layers`` and lets XLA
  pipeline HBM prefetch of the next layer's weights.
* Batch dimension is ``[B, T]`` *sequences × positions* — the reference's
  positions-as-batch prefill (nBatches, SURVEY.md §2.2) is the ``B=1`` case.
* Activations carry logical axis names via
  :func:`dllama_tpu.parallel.constrain` so the same code runs single-chip or
  sharded over any mesh.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.mfile import ArchType, HiddenAct, ModelFile, RopeType
from ..formats.quants import Q40
from ..ops import flash_attention as _fa
from ..ops.attention import attention
from ..ops.flash_attention import flash_attention
from ..ops.linear import (
    QuantizedWeight,
    Weight,
    fake_quant_q80,
    linear,
    quantize_weight_q40,
)
from ..ops.norms import rms_norm, rms_norm_per_head
from ..parallel.api import constrain, shard_map
from ..parallel.api import current_plan as _current_plan
from ..runtime import numerics as _numerics
from ..runtime.kvcache import KVCache, update_layer
from .config import ModelConfig
from .rope import apply_rope, build_rope_cache


class LayerParams(NamedTuple):
    """Per-layer weights; every leaf carries a leading ``[n_layers]`` axis."""

    wq: Weight  # [L, q_dim, dim]
    wk: Weight  # [L, kv_dim, dim]
    wv: Weight  # [L, kv_dim, dim]
    wo: Weight  # [L, dim, q_dim]
    w1: Weight | None  # [L, hidden_dim, dim]   (gate; None for MoE layers)
    w2: Weight | None  # [L, dim, hidden_dim]   (down)
    w3: Weight | None  # [L, hidden_dim, dim]   (up)
    norm_att: jax.Array  # [L, dim]
    norm_ffn: jax.Array  # [L, dim]
    norm_q: jax.Array | None  # [L, head_dim] (qwen3) or None
    norm_k: jax.Array | None
    # MoE (None for dense models). Expert weights carry any Weight repr:
    # dense (compute dtype), stacked QuantizedWeight (Q40/Q80 planes — 1
    # B/weight resident, dequant fused into the consuming dot), or
    # TurboWeight after turbo derivation. Layout is IN-major
    # ("[.., in, out]") so ``lax.ragged_dot``'s grouped matmul consumes the
    # dense planes with no per-step transpose (its rhs contracts axis 1).
    moe_gate: jax.Array | None = None  # [L, E, dim] router
    we1: Weight | None = None          # [L, E, dim, hidden_dim] (gate)
    we2: Weight | None = None          # [L, E, hidden_dim, dim] (down)
    we3: Weight | None = None          # [L, E, dim, hidden_dim] (up)


class Params(NamedTuple):
    embedding: jax.Array  # [vocab, dim]
    layers: LayerParams
    final_norm: jax.Array  # [dim]
    logits: Weight  # [vocab, dim]


def _use_flash(cfg: ModelConfig, q_shape, kv_shape) -> bool:
    """Trace-time choice of the single-device attention kernel. Under a mesh
    plan the auto-sharder cannot partition a pallas_call — the TP path wraps
    the kernel in shard_map (flash_attention_sharded) and the SP path has its
    own kernels (parallel/ring.py). Exception: a PURE-pp mesh — inside the
    manual pp shard_map with no other mesh axes every stage's arrays are
    fully local, so the plain kernel applies per stage."""
    from ..parallel.api import current_plan

    if cfg.attn_impl not in ("auto", "xla", "flash"):
        raise ValueError(f"attn_impl must be auto|xla|flash, got {cfg.attn_impl!r}")
    if cfg.attn_impl == "xla":
        return False
    plan = current_plan()
    plan_ok = plan is None or (
        plan.axis_size("pp") > 1
        and all(plan.axis_size(a) == 1 for a in ("tp", "sp", "dp", "ep")))
    n_kv, s = kv_shape[1], kv_shape[2]
    ok = _fa.supports(q_shape, n_kv, s)
    if cfg.attn_impl == "flash":
        if not ok:
            raise ValueError(f"flash attention unsupported for q={q_shape}, S={s}")
        if plan is not None and plan.axis_size("pp") > 1 and not plan_ok:
            # direct-forward pp meshes with extra axes never pass through
            # validate_pp: a forced kernel must still fail loudly here, not
            # silently run the oracle
            raise ValueError(
                "attn_impl='flash' under pp×(tp|dp|sp|ep) is unsupported "
                "(the Pallas kernel can't nest inside the manual pp "
                "shard_map with auto axes); use 'auto' or 'xla', or pure pp")
        return plan_ok
    return ok and _fa.default_enabled() and plan_ok


def _sharded_flash(cfg: ModelConfig, plan, q, k_cache, v_cache, start_pos):
    """TP-path Pallas attention via shard_map; None → caller uses the oracle.

    ``attn_impl='flash'`` forces it (interpret mode off-TPU, for tests) and
    FAILS LOUDLY when the plan/shape can't take the kernel — a forced mode
    silently falling back to the oracle hid exactly the configurations the
    user asked to exercise (advisor round-1 finding); ``'auto'`` enables it
    on TPU backends only."""
    if cfg.attn_impl == "xla":
        return None
    if plan.axis_size("pp") > 1:
        # inside the manual pp shard_map a nested pallas shard_map can't
        # partition; per-stage attention uses the XLA oracle when other
        # axes are in play (validate_pp rejects forced 'flash' for
        # pp×(tp|dp|sp); PURE pp runs the plain kernel via _use_flash)
        return None
    if plan.axis_size("sp") > 1:
        # sp attention is owned by the ring path (parallel/ring.py); landing
        # here means sp_attention declined the geometry (S % sp != 0, an
        # irregular head split, or B % dp != 0) and the oracle serves the
        # fallback — which a forced 'flash' must surface, not paper over
        if cfg.attn_impl == "flash":
            raise ValueError(
                f"attn_impl='flash' forced but the sp ring path declined "
                f"this geometry (plan axes "
                f"{dict(zip(plan.mesh.axis_names, plan.mesh.devices.shape))}, "
                f"q={q.shape}, kv={k_cache.shape}; needs S % sp == 0, a "
                f"regular head split, and B % dp == 0) — drop attn_impl or "
                f"use 'auto'")
        return None
    force = cfg.attn_impl == "flash"
    if not force and not _fa.default_enabled():
        return None
    res = _fa.flash_attention_sharded(
        plan, q, k_cache, v_cache, start_pos, cfg.head_dim,
        interpret=force and not _fa.default_enabled())
    if res is None and force:
        raise ValueError(
            f"attn_impl='flash' forced but the sharded kernel does not apply "
            f"(plan axes {dict(zip(plan.mesh.axis_names, plan.mesh.devices.shape))}, "
            f"q={q.shape}, kv={k_cache.shape}; irregular q-head/kv-group "
            f"splits (tp % n_kv != 0 with n_kv % tp != 0) use the XLA "
            f"oracle — drop attn_impl or use 'auto')")
    return res


def _hidden_act(cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.hidden_act == HiddenAct.SILU:
        return jax.nn.silu(x)
    # tanh-approx gelu (reference: gelu_F32, nn-cpu-ops.cpp:1133-1142)
    return jax.nn.gelu(x, approximate=True)


def _moe_router(cfg: ModelConfig, h: jax.Array, gate: jax.Array):
    """Top-k routing (shared by both MoE impls): softmax over all expert
    logits, top-k, then either renormalize the selected weights to sum to 1
    (cfg.moe_norm_topk — Mixtral semantics; renormalizing equals softmaxing
    the selected logits) or keep the raw probabilities (Qwen3-MoE with HF
    norm_topk_prob false). Returns ``(weights [.., k], idx [.., k])``."""
    logits = jnp.einsum("...d,ed->...e", h.astype(jnp.float32),
                        gate.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    top, idx = jax.lax.top_k(probs, cfg.n_active_experts)
    if cfg.moe_norm_topk:
        top = top / jnp.sum(top, axis=-1, keepdims=True)
    return top, idx


def _experts_dense(we, x: jax.Array, rows: jax.Array | None = None) -> jax.Array:
    """Dense ``[..., in, out]`` planes of an expert-stack weight (inside the
    layer scan: ``[E, in, out]``), optionally gathered at ``rows`` along the
    leading expert axis first (gathering the QUANTIZED planes keeps the HBM
    read at 1 B/weight — the dequant expansion happens on the k gathered
    slices only, and XLA fuses it into the consuming dot, the same fused-
    dequant fast path ops.linear uses)."""
    from ..ops.linear import QuantizedWeight, _fast_mode, dequantize_weight
    from ..ops.turbo import TurboWeight

    if isinstance(we, QuantizedWeight):
        if rows is not None:
            we = QuantizedWeight(scales=we.scales[rows], codes=we.codes[rows])
        fast = _fast_mode(x) or we.scales.dtype == jnp.bfloat16
        return dequantize_weight(we, dtype=jnp.bfloat16 if fast else x.dtype)
    if isinstance(we, TurboWeight):
        w8 = we.w8 if rows is None else we.w8[rows]
        scale = we.scale if rows is None else we.scale[rows]
        # per-column scales: ONE multiply per element (half the fast path's
        # per-element convert+scale); the ragged/dense consumers need a
        # dense rhs, so the s8 dot itself is not used on this path
        return w8.astype(jnp.bfloat16) * scale[..., None, :].astype(jnp.bfloat16)
    return we if rows is None else we[rows]


def _expert_gather_dot(x: jax.Array, we, rows: jax.Array) -> jax.Array:
    """``y[n] = x[n] @ plane(rows[n])`` — the decode-regime per-row expert
    dot. ``x [N, D]``, result f32 ``[N, out]``. TurboWeight runs its real
    integer-dot contraction (scales in the epilogue, ops.turbo semantics);
    other reprs gather-then-dequant via :func:`_experts_dense`."""
    from ..ops.turbo import TurboWeight

    if isinstance(we, TurboWeight):
        w8 = we.w8[rows]                       # [N, D, out] int8
        scale = we.scale[rows]                 # [N, out] f32
        if we.a8:
            from ..ops.turbo import quantize_activations_a8

            xq, sx = quantize_activations_a8(x)
            acc = jnp.einsum("nd,ndh->nh", xq, w8,
                             preferred_element_type=jnp.int32)
            return acc.astype(jnp.float32) * sx * scale
        acc = jnp.einsum("nd,ndh->nh", x.astype(jnp.bfloat16),
                         w8.astype(jnp.bfloat16),
                         preferred_element_type=jnp.float32)
        return acc * scale
    w = _experts_dense(we, x, rows)
    return jnp.einsum("nd,ndh->nh", x.astype(w.dtype), w,
                      preferred_element_type=jnp.float32)


def _moe_ffn_dense(cfg: ModelConfig, h: jax.Array, lp: LayerParams) -> jax.Array:
    """All-experts einsum, gate-weighted — O(E) FLOPs but exact and simple;
    the oracle the sparse path is tested against, and the fallback when the
    mesh shards the expert-hidden axis over tp."""
    E = cfg.n_experts
    weights, idx = _moe_router(cfg, h, lp.moe_gate)
    one_hot = jax.nn.one_hot(idx, E, dtype=jnp.float32)      # [B,T,k,E]
    gates = jnp.einsum("btke,btk->bte", one_hot, weights)    # sparse rows
    gates = constrain(gates, "batch", None, "experts")

    ht = h.astype(cfg.compute_dtype)
    we1 = _experts_dense(lp.we1, ht)
    we2 = _experts_dense(lp.we2, ht)
    we3 = _experts_dense(lp.we3, ht)
    h1 = jnp.einsum("btd,edh->bteh", ht, we1)
    h3 = jnp.einsum("btd,edh->bteh", ht, we3)
    a = _hidden_act(cfg, h1) * h3
    a = constrain(a, "batch", None, "experts", "hidden")
    y = jnp.einsum("bteh,ehd,bte->btd", a, we2,
                   gates.astype(cfg.compute_dtype))
    return y.astype(h.dtype)


# Below this many (token, expert) rows the sparse path gathers per-row expert
# weights instead of sorting into ragged groups: at decode (N·k ~ a few) the
# gathered weights are tiny and the compute is exactly O(k) on EVERY backend,
# whereas ragged_dot's fallback lowering is a masked dense over all groups.
_MOE_GATHER_MAX_ROWS = 32


def _moe_sparse_local(cfg: ModelConfig, x: jax.Array, idx: jax.Array,
                      weights: jax.Array, we1, we2, we3,
                      e_lo: jax.Array, e_local: int) -> jax.Array:
    """Sparse MoE over this device's expert slice ``[e_lo, e_lo+e_local)``.

    ``x [N, D]``, ``idx/weights [N, k]``. Rows routed to non-local experts are
    clamped to expert 0 with weight 0 (computed-then-discarded — N·k rows per
    device keeps shapes static; still O(k), not O(E), work per token).

    Two regimes: decode-sized inputs gather the k experts' weight slices per
    row (true O(k) FLOPs, small transient); prefill-sized inputs sort rows by
    expert and run one ``lax.ragged_dot`` grouped matmul per projection.
    """
    N, k = idx.shape
    flat_e = idx.reshape(N * k) - e_lo
    valid = (flat_e >= 0) & (flat_e < e_local)
    flat_e = jnp.where(valid, flat_e, 0)
    flat_w = jnp.where(valid, weights.reshape(N * k), 0.0)
    x_rep = x[jnp.arange(N * k, dtype=jnp.int32) // k]  # row per (token, k)

    if N * k <= _MOE_GATHER_MAX_ROWS:
        h1 = _expert_gather_dot(x_rep, we1, flat_e)
        h3 = _expert_gather_dot(x_rep, we3, flat_e)
        a = (_hidden_act(cfg, h1) * h3).astype(x.dtype)
        y = _expert_gather_dot(a, we2, flat_e)
        y = y * flat_w[:, None]
    else:
        order = jnp.argsort(flat_e)                    # group rows by expert
        xs = x_rep[order]
        group_sizes = jnp.bincount(flat_e, length=e_local).astype(jnp.int32)
        # ragged_dot needs a dense rhs: quantized/turbo planes expand to a
        # bf16 transient of this device's local expert slice here (prefill
        # regime — MXU-bound, so the extra HBM of the expansion is paid
        # where it is cheapest; decode takes the gather regime above)
        d1 = _experts_dense(we1, xs)
        d2 = _experts_dense(we2, xs)
        d3 = _experts_dense(we3, xs)

        h1 = jax.lax.ragged_dot(xs.astype(d1.dtype), d1, group_sizes,
                                preferred_element_type=jnp.float32)
        h3 = jax.lax.ragged_dot(xs.astype(d3.dtype), d3, group_sizes,
                                preferred_element_type=jnp.float32)
        a = (_hidden_act(cfg, h1) * h3).astype(d2.dtype)
        y = jax.lax.ragged_dot(a, d2, group_sizes,
                               preferred_element_type=jnp.float32)
        y = y[jnp.argsort(order)] * flat_w[:, None]    # unsort to [N*k]
    return jnp.sum(y.reshape(N, k, -1), axis=1).astype(x.dtype)


def _moe_ffn_sparse(cfg: ModelConfig, h: jax.Array, lp: LayerParams) -> jax.Array:
    """Sparse top-k dispatch: tokens sorted by expert, one ``lax.ragged_dot``
    per projection — O(k/E) of the dense path's FFN FLOPs (the whole point of
    MoE; beyond-reference capability, SURVEY.md §2.2). Runs inside shard_map
    under a mesh: experts shard over ``ep`` (each device computes its local
    expert groups, psum combines), batch shards over ``dp``."""
    B, T, D = h.shape
    weights, idx = _moe_router(cfg, h, lp.moe_gate)
    x = h.astype(cfg.compute_dtype).reshape(B * T, D)
    idx2 = idx.reshape(B * T, cfg.n_active_experts)
    w2 = weights.astype(cfg.compute_dtype).reshape(B * T, cfg.n_active_experts)

    plan = _current_plan()
    if plan is None or plan.axis_size("pp") > 1:
        # no mesh, or already inside the manual pp shard_map (nesting another
        # shard_map is unsupported): run the sparse path stage-locally with
        # the full expert set
        y = _moe_sparse_local(cfg, x, idx2, w2, lp.we1, lp.we2, lp.we3,
                              jnp.int32(0), cfg.n_experts)
        return y.reshape(B, T, D).astype(h.dtype)

    from jax.sharding import PartitionSpec as P

    ep_ax = plan.resolve("experts")
    if ep_ax is not None and cfg.n_experts % plan._axis_size(ep_ax) != 0:
        ep_ax = None
    # tp shards the expert-hidden axis (param_shardings lays we1/we3 out as
    # [E(ep), D, H(tp)] and we2 as [E(ep), H(tp), D]): each device runs the
    # sparse dispatch over its H-slice — SiLU/GELU are elementwise over H, so
    # the act(h1)*h3 product is exact per-shard — and the we2 contraction's
    # H-partials psum together with the ep partials. This is col-split FFN
    # semantics (reference sliceColMatmul, nn-core.cpp:219-230) composed with
    # expert parallelism; previously a hidden-sharded mesh silently paid the
    # dense all-experts O(E) fallback (VERDICT r3 weak #3).
    from ..ops.linear import QuantizedWeight

    hid_ax = plan.resolve("hidden")
    if hid_ax is not None and (plan._axis_size(hid_ax) == 1
                               or cfg.hidden_dim % plan._axis_size(hid_ax) != 0):
        hid_ax = None
    from ..formats.quants import QUANT_BLOCK_SIZE

    if (hid_ax is not None and isinstance(lp.we2, QuantizedWeight)
            and (cfg.hidden_dim // QUANT_BLOCK_SIZE)
            % plan._axis_size(hid_ax) != 0):
        # we2's scale plane is [E, H/32, D]: an H-shard must also divide the
        # 32-element block axis or the scales can't split with the codes
        hid_ax = None
    e_local = cfg.n_experts // (plan._axis_size(ep_ax) if ep_ax else 1)
    red_axes = tuple(a for a in (ep_ax, hid_ax) if a is not None)

    from ..parallel.qcollectives import wire_psum

    ax_sizes = tuple(plan._axis_size(a) for a in red_axes)

    def local(x_l, idx_l, w_l, we1, we2, we3):
        e_lo = (jax.lax.axis_index(ep_ax) * e_local) if ep_ax else jnp.int32(0)
        y = _moe_sparse_local(cfg, x_l, idx_l, w_l, we1, we2, we3, e_lo, e_local)
        return wire_psum(y, red_axes, ax_sizes) if red_axes else y

    def we_spec(we, *, hid_on_out: bool):
        """Per-leaf PartitionSpecs for one expert-stack weight [E, in, out]:
        the per-repr plane layout comes from the ONE place that defines it
        (parallel.sharding.map_expert_weight), with the logical "hidden"
        axis resolved to this mesh's hid_ax."""
        from ..parallel.sharding import map_expert_weight

        in_ax, out_ax = (None, "hidden") if hid_on_out else ("hidden", None)
        return map_expert_weight(
            we, in_ax, out_ax,
            lambda _leaf, axes: P(ep_ax, *(hid_ax if a == "hidden" else None
                                           for a in axes)))

    fn = shard_map(
        local, mesh=plan.mesh,
        in_specs=(P(), P(), P(),
                  we_spec(lp.we1, hid_on_out=True),
                  we_spec(lp.we2, hid_on_out=False),
                  we_spec(lp.we3, hid_on_out=True)),
        out_specs=P(),
        check_vma=False)
    y = fn(x, idx2, w2, lp.we1, lp.we2, lp.we3)
    return y.reshape(B, T, D).astype(h.dtype)


def _moe_ffn(cfg: ModelConfig, h: jax.Array, lp: LayerParams) -> jax.Array:
    """Mixture-of-experts SwiGLU FFN — new capability (the reference parses
    N_EXPERTS but its graph builder never emits expert ops, SURVEY.md §2.2).

    cfg.moe_impl picks the compute: "sparse" (grouped ragged_dot, default) or
    "dense" (all-experts oracle). The sparse path shards experts over ep AND
    the expert-hidden axis over tp (col-split partials, psum-combined); only
    a non-divisible hidden shard degrades to dense, whose einsums tolerate
    the replicated layout sharding_for falls back to.
    """
    impl = cfg.moe_impl
    plan = _current_plan()
    if impl == "auto":
        impl = "sparse"
    if impl == "sparse" and plan is not None:
        hid_ax = plan.resolve("hidden")
        if hid_ax is not None and plan._axis_size(hid_ax) > 1 \
                and cfg.hidden_dim % plan._axis_size(hid_ax) != 0:
            impl = "dense"
    if impl == "sparse":
        return _moe_ffn_sparse(cfg, h, lp)
    return _moe_ffn_dense(cfg, h, lp)


def _tap_stat(x: jax.Array) -> dict[str, jax.Array]:
    """Activation stats for one numerics-observatory tap site (all f32/i32
    scalars, cheap reductions XLA fuses into the producing op's epilogue):
    rms and abs-max over FINITE lanes (a NaN must poison the non-finite
    count, not the statistics), the non-finite lane count, and the Q80
    roundtrip error the sync/wire quantization would apply at this
    boundary (0 when the trailing axis isn't block-divisible)."""
    from ..formats.quants import Q80_BLOCK_SIZE
    from ..parallel.qcollectives import q80_roundtrip_error

    xf = x.astype(jnp.float32)
    finite = jnp.isfinite(xf)
    nf = jnp.sum(jnp.logical_not(finite).astype(jnp.int32))
    xz = jnp.where(finite, xf, 0.0)
    rms = jnp.sqrt(jnp.mean(jnp.square(xz)))
    absmax = jnp.max(jnp.abs(xz))
    q80e = (q80_roundtrip_error(xz) if x.shape[-1] % Q80_BLOCK_SIZE == 0
            else jnp.float32(0.0))
    return {"rms": rms, "absmax": absmax, "nonfinite": nf, "q80_err": q80e}


# Widest dispatch that still counts as the decode regime for the overlapped
# merges: single steps (T=1), fused-chunk scan bodies (T=1), and speculative
# verifies (T=K+1, small) ride the ring; prefill chunks (T >= 32) keep the
# monolithic GSPMD psum — they are MXU-bound, so chunking their merge would
# add launch overhead where there is no exposed collective wall to hide.
_OVERLAP_MAX_WIDTH = 16


def _overlapped_col_linear(cfg: ModelConfig, x: jax.Array, w,
                           in_logical: str):
    """TokenWeave-shaped col-split projection: the local partial matmul and
    a CHUNKED ring merge inside one shard_map, so XLA can schedule chunk
    i's ``ppermute`` hops concurrently with chunk j's dequant/accumulate
    compute (parallel/qcollectives.overlapped_wire_psum; the q80 wire rides
    the same hops when ``--wire q80``). Returns None when this geometry
    keeps the monolithic GSPMD path: no plan / no tp resolution for
    ``in_logical`` / non-divisible shapes / sp-pp meshes (their manual
    regions can't nest another shard_map) / turbo weights (their integer
    dot is fused per shard in ops.turbo) / prefill-wide dispatches."""
    from jax.sharding import PartitionSpec as P

    from ..formats.quants import Q40_BLOCK_SIZE
    from ..ops.linear import _fast_mode, dequantize_weight
    from ..ops.turbo import TurboWeight
    from ..parallel.qcollectives import overlapped_wire_psum

    plan = _current_plan()
    if (cfg.comm_overlap <= 1 or plan is None or x.ndim != 3
            or isinstance(w, TurboWeight)
            or x.shape[1] > _OVERLAP_MAX_WIDTH
            or any(plan.axis_size(a) > 1 for a in ("sp", "pp"))):
        return None
    B, T, K = x.shape
    k_ax = plan.resolve(in_logical)
    if k_ax is None or K % plan._axis_size(k_ax) != 0:
        return None
    n = plan._axis_size(k_ax)
    if n <= 1 or cfg.dim % cfg.comm_overlap != 0:
        return None
    dp_ax = plan.resolve("batch")
    if dp_ax is not None and B % plan._axis_size(dp_ax) != 0:
        dp_ax = None
    quant = isinstance(w, QuantizedWeight)
    if quant and (K // n) % Q40_BLOCK_SIZE != 0:
        return None  # the scale plane's block rows can't split with codes
    fast = quant and (_fast_mode(x) or w.scales.dtype == jnp.bfloat16)
    out_dtype = x.dtype

    def local(xl, *wl):
        # f32 partials so the cross-device reduction doesn't round in bf16
        # (same rule as quant_matmul_sharded's col-split merge)
        if quant:
            from ..ops.quant_matmul import pallas_local_choice, quant_matmul

            sc, cd = wl
            lw = QuantizedWeight(scales=sc, codes=cd)
            # the ONE shared kernel rule (quant_matmul.pallas_local_choice)
            # — flipping --comm-overlap never silently swaps the local
            # matmul's numerics
            kernel = pallas_local_choice(tuple(xl.shape), lw, fast)
            if kernel is not None:
                part = quant_matmul(xl.astype(jnp.float32), lw,
                                    fast=fast, **kernel)
            else:
                wd = dequantize_weight(
                    lw, dtype=jnp.bfloat16 if fast else xl.dtype)
                part = jax.lax.dot_general(
                    xl.astype(wd.dtype), wd,
                    dimension_numbers=(((2,), (0,)), ((), ())),  # [K, D]
                    preferred_element_type=jnp.float32)
        else:
            wd = wl[0].astype(xl.dtype)
            part = jax.lax.dot_general(
                xl, wd,
                dimension_numbers=(((2,), (1,)), ((), ())),  # dense [D, K]
                preferred_element_type=jnp.float32)
        merged = overlapped_wire_psum(part, k_ax, n, cfg.comm_overlap)
        return merged.astype(out_dtype)

    if quant:
        w_specs = (P(k_ax, None), P(k_ax, None))  # scales, codes shard K
        w_leaves = (w.scales, w.codes)
    else:
        w_specs = (P(None, k_ax),)  # dense [out, in] shards the in dim
        w_leaves = (w,)
    fn = shard_map(
        local, mesh=plan.mesh,
        in_specs=(P(dp_ax, None, k_ax), *w_specs),
        out_specs=P(dp_ax, None, None), check_vma=False)
    from ..parallel.qcollectives import wire_poison_dp_scope

    # under dp the shard-local "row 0" exists per dp group: name the axis
    # so the wire poison site can pin the GLOBAL row 0 (one request)
    with wire_poison_dp_scope(dp_ax):
        return fn(x, *w_leaves)


def _merge_linear(cfg: ModelConfig, x: jax.Array, w, in_logical: str):
    """One col-split partial merge (wo or w2): the overlapped ring path
    when ``--comm-overlap`` resolved chunks for this geometry, else the
    plain :func:`linear` col-split (GSPMD psum / sharded Pallas kernel)."""
    y = _overlapped_col_linear(cfg, x, w, in_logical)
    if y is not None:
        return y
    return linear(x, w, in_axis=in_logical)


def _attn_qkv(cfg: ModelConfig, x: jax.Array, lp: LayerParams,
              cos: jax.Array, sin: jax.Array, positions: jax.Array, fq):
    """Attention prologue shared by the dense and paged layer steps:
    pre-norm, QKV projections, optional qk-norm, rope. Returns post-rope
    ``q [B, T, n_heads, hd]`` and ``k/v [B, T, n_kv, hd]``."""
    B, T, _ = x.shape
    h = fq(rms_norm(x, lp.norm_att, cfg.norm_epsilon))
    q = linear(h, lp.wq, out_axis="heads").reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = linear(h, lp.wk, out_axis="kv_heads").reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = linear(h, lp.wv, out_axis="kv_heads").reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    q = constrain(q, "batch", None, "heads", None)
    k = constrain(k, "batch", None, "kv_heads", None)
    v = constrain(v, "batch", None, "kv_heads", None)

    if cfg.uses_qk_norm:
        q = rms_norm_per_head(q, lp.norm_q, cfg.norm_epsilon)
        k = rms_norm_per_head(k, lp.norm_k, cfg.norm_epsilon)

    q = apply_rope(q, cos, sin, positions, cfg.rope_type)
    k = apply_rope(k, cos, sin, positions, cfg.rope_type)
    return q, k, v


def _attn_out_and_ffn(cfg: ModelConfig, x: jax.Array, att: jax.Array,
                      lp: LayerParams, fq, taps: bool):
    """Layer epilogue shared by the dense and paged layer steps: output
    projection + residual, then the ffn half. Returns ``(x, stats|None)``."""
    B, T, _ = x.shape
    x = x + fq(_merge_linear(cfg, fq(att.reshape(B, T, cfg.q_dim)), lp.wo,
                             "heads"))
    x = constrain(x, "batch", None, None)
    attn_stat = _tap_stat(x) if taps else None

    # -- ffn half (reference ff segment, llm.cpp:369-439; MoE is new) ------
    h = fq(rms_norm(x, lp.norm_ffn, cfg.norm_epsilon))
    if cfg.is_moe:
        x = x + fq(_moe_ffn(cfg, h, lp))
    else:
        gate = _hidden_act(cfg, linear(h, lp.w1, out_axis="hidden"))
        up = linear(h, lp.w3, out_axis="hidden")
        hidden = constrain(fq(gate * up), "batch", None, "hidden")
        x = x + fq(_merge_linear(cfg, hidden, lp.w2, "hidden"))
    x = constrain(x, "batch", None, None)
    if taps:
        return x, {"attn_out": attn_stat, "mlp_out": _tap_stat(x)}
    return x, None


def _layer_step(cfg: ModelConfig, x: jax.Array, lp: LayerParams,
                k_cache: jax.Array, v_cache: jax.Array,
                cos: jax.Array, sin: jax.Array, start_pos: jax.Array,
                positions: jax.Array, taps: bool = False):
    """One transformer block. ``x: [B, T, dim]``; caches are head-major
    ``[B, n_kv, S, hd]`` (see runtime.kvcache). With ``taps`` (a
    trace-time bool — the numerics observatory's activation taps) the
    return gains a per-site stats dict: ``attn_out`` after the attention
    residual, ``mlp_out`` after the ffn residual."""
    B, T, _ = x.shape

    # Q80 sync-parity: fake-quantize at the reference's cast points — matmul
    # inputs (X→Q80 casts) and the partial-sum outputs that cross the wire
    # (ZQ pipe casts, llm.cpp:258-265, 360-365, 433-438).
    fq = fake_quant_q80 if cfg.sync_q80 else (lambda a: a)

    # -- attention half (reference att segment, llm.cpp:226-366) -----------
    q, k, v = _attn_qkv(cfg, x, lp, cos, sin, positions, fq)

    sp_res = None
    plan = _current_plan()
    if plan is not None and plan.axis_size("sp") > 1 \
            and plan.axis_size("pp") == 1:  # sp×pp nesting unsupported
        from ..parallel.ring import sp_attention

        # ragged rides the same ring/merge paths: positions are affine
        # WITHIN each batch row, which is all the per-row kernel pos table
        # and the [B, T] masks assume; the per-slot append depths shard
        # with the batch rows
        sp_res = sp_attention(plan, q, k_cache, v_cache, k, v, positions,
                              start_pos, cfg.head_dim, attn_impl=cfg.attn_impl)
    if sp_res is not None:
        att, k_cache, v_cache = sp_res
    else:
        k_cache, v_cache = update_layer(k_cache, v_cache, k, v, start_pos)
        # ragged (per-row positions) rides the same kernels: the flash
        # kernel's position table is blocked per batch row
        att = (_sharded_flash(cfg, plan, q, k_cache, v_cache, start_pos)
               if plan is not None else None)
        if att is None:
            if _use_flash(cfg, q.shape, k_cache.shape):
                # forced 'flash' off-TPU runs the kernel in interpret mode
                # (the test path, same rule _sharded_flash applies)
                att = flash_attention(
                    q, k_cache, v_cache, start_pos, cfg.head_dim,
                    interpret=(cfg.attn_impl == "flash"
                               and not _fa.default_enabled()))
            else:
                att = attention(q, k_cache, v_cache, positions, cfg.head_dim)
    att = constrain(att, "batch", None, "heads", None)
    x, stats = _attn_out_and_ffn(cfg, x, att, lp, fq, taps)
    if taps:
        return x, k_cache, v_cache, stats
    return x, k_cache, v_cache


def _paged_layer_step(cfg: ModelConfig, x: jax.Array, lp: LayerParams,
                      k_pool: jax.Array, v_pool: jax.Array,
                      cos: jax.Array, sin: jax.Array,
                      positions: jax.Array, tables: jax.Array,
                      write_lens: jax.Array | None = None):
    """One transformer block over the PAGED cache (runtime/kvblocks.py).

    ``k_pool/v_pool: [n_blocks, n_kv, block_size, hd]`` is this layer's
    slice of the block pool; ``tables [B, max_blocks]`` maps each row's
    logical block index to a physical block (0 = the null block). New K/V
    rows scatter into their physical (block, offset) cell, then the row's
    logical cache is gathered back to the dense head-major view and
    attended by the XLA oracle — value-identical to the dense slot-pool
    layer step on the same context (the gather materializes exactly the
    rows ``update_layer`` would have produced; rows behind unallocated
    table entries read the null block and are position-masked). The
    TPU-native ragged-paged-attention kernel (ops/paged_attention.py,
    PAPERS.md "Ragged Paged Attention") replaces the gather+oracle pair
    bit-identically whenever its gate resolves — same callers, same
    program names, zero extra compiles."""
    from ..ops import paged_attention as _pa

    B, T, _ = x.shape
    fq = fake_quant_q80 if cfg.sync_q80 else (lambda a: a)
    q, k, v = _attn_qkv(cfg, x, lp, cos, sin, positions, fq)

    bs = k_pool.shape[2]
    n_blocks_seq = tables.shape[1]
    brow = jnp.arange(B, dtype=jnp.int32)[:, None]
    blk = tables[brow, positions // bs]                      # [B, T]
    off = positions % bs
    if write_lens is not None:
        # ragged verify (paged_verify_step): lane t of row b is a real
        # input only while t <= write_lens[b] — lanes past the row's
        # draft length carry padding whose writes must not consume (or
        # corrupt) cells the host never allocated blocks for. Redirect
        # them to the null block; traced, so varying per-slot draft
        # lengths never retrace.
        lane = jnp.arange(T, dtype=jnp.int32)[None, :]
        blk = jnp.where(lane <= write_lens[:, None], blk, 0)
    # scatter the new rows: advanced (blk, off) indices around the head
    # slice address each row's [n_kv, hd] cell; inactive rows carry
    # all-null tables, so their ride-along writes land in the null block
    k_pool = k_pool.at[blk, :, off, :].set(k.astype(k_pool.dtype))
    v_pool = v_pool.at[blk, :, off, :].set(v.astype(v_pool.dtype))

    kernel = _pa.kernel_choice(tuple(q.shape), cfg.n_kv_heads,
                               n_blocks_seq, bs)
    if kernel is not None:
        # walk the block table in-kernel: the dense logical cache never
        # materializes in HBM (the whole point of the paged kernel)
        att = _pa.paged_ragged_attention(q, k_pool, v_pool, tables,
                                         positions, cfg.head_dim, **kernel)
    else:
        def view(pool):
            gathered = pool[tables]              # [B, M, n_kv, bs, hd]
            return jnp.moveaxis(gathered, 2, 1).reshape(
                B, cfg.n_kv_heads, n_blocks_seq * bs, cfg.head_dim)

        att = attention(q, view(k_pool), view(v_pool), positions,
                        cfg.head_dim)
    att = constrain(att, "batch", None, "heads", None)
    x, _ = _attn_out_and_ffn(cfg, x, att, lp, fq, taps=False)
    return x, k_pool, v_pool


def greedy_step(params: Params, cfg: ModelConfig, tokens: jax.Array,
                start_pos: jax.Array, kv: KVCache) -> tuple[jax.Array, KVCache]:
    """Fused forward + argmax of the last position — the single-dispatch
    greedy decode step (SURVEY.md §7.4 "single fused jitted step"). Shared by
    the engine's fast path and bench.py so the benchmark measures the
    production program."""
    logits, kv = forward(params, cfg, tokens, start_pos, kv)
    return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), kv


def verify_step(params: Params, cfg: ModelConfig, tokens: jax.Array,
                start_pos: jax.Array, kv: KVCache
                ) -> tuple[jax.Array, jax.Array, KVCache]:
    """Speculative greedy verify: ONE forward over ``tokens [B, K+1]`` (the
    real next input followed by K drafted tokens) at positions
    ``start_pos..start_pos+K``; ``preds[:, t]`` is the greedy argmax after
    consuming ``tokens[:, :t+1]`` and ``n_acc`` is the longest draft prefix
    the model agrees with (``tokens[:, i+1] == preds[:, i]``). The caller
    emits ``preds[:, :n_acc+1]`` — exactly what n_acc+1 sequential
    greedy_step calls would produce, for one dispatch whose HBM cost is a
    single decode step (weights dominate; the K extra rows ride the same
    weight reads on the MXU).

    KV safety is the decode-chunk argument (engine module docstring): rows
    written for rejected drafts sit at positions > the committed point,
    invisible to the causal mask, and the next dispatch's K+1 writes start
    exactly where the stale region starts. No reference analogue — the
    reference decodes strictly one token per step (dllama.cpp:88-99)."""
    logits, kv = forward(params, cfg, tokens, start_pos, kv)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
    ok = (tokens[:, 1:] == preds[:, :-1]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(ok, axis=-1), axis=-1)  # [B]
    return n_acc, preds, kv


def ragged_verify_step(params: Params, cfg: ModelConfig, tokens: jax.Array,
                       pos_vec: jax.Array, kv: KVCache, temps: jax.Array,
                       topps: jax.Array, coins: jax.Array
                       ) -> tuple[jax.Array, jax.Array, KVCache]:
    """Batched-serving twin of :func:`verify_step`: one verify dispatch over
    ragged rows ``tokens [B, K+1]`` at per-row positions ``pos_vec [B]``.
    Greedy rows (temp <= 0) accept the longest draft prefix exactly as the
    single-sequence path does; sampled rows consume their one coin on the
    position-0 logits and accept nothing — their token/coin streams are
    bit-identical to the plain ragged step, so per-request determinism (the
    serving invariant) survives speculation joining the batch."""
    from ..ops.sampling import sampled_token

    logits, kv = forward(params, cfg, tokens, pos_vec, kv)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
    ok = (tokens[:, 1:] == preds[:, :-1]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(ok, axis=-1), axis=-1)
    greedy_row = jnp.asarray(temps) <= 0.0
    n_acc = jnp.where(greedy_row, n_acc, 0)
    first = sampled_token(logits[:, 0], temps, topps, coins)
    preds = preds.at[:, 0].set(first)  # greedy rows: first == argmax already
    return n_acc, preds, kv


def scan_decode(step1, token: jax.Array, start_pos: jax.Array, kv: KVCache,
                n_steps: int, coins: jax.Array | None = None):
    """The one multi-step decode scan shared by every chunked variant
    (greedy/sampled × plain/replicated): feeds each picked token into the
    next forward on device. ``step1(tokens_2d, pos, kv[, coin])`` is the
    single-step function; returns ``(tokens [B, n_steps], kv)``."""

    def body(carry, xs):
        token, kv = carry
        if coins is None:
            nxt, kv = step1(token[:, None], start_pos + xs, kv)
        else:
            i, coin = xs
            nxt, kv = step1(token[:, None], start_pos + i, kv, coin)
        return (nxt, kv), nxt

    xs = jnp.arange(n_steps, dtype=jnp.int32)
    (_, kv), toks = jax.lax.scan(
        body, (token, kv), xs if coins is None else (xs, coins))
    return jnp.moveaxis(toks, 0, 1), kv  # [B, n_steps]


def greedy_steps(params: Params, cfg: ModelConfig, token: jax.Array,
                 start_pos: jax.Array, kv: KVCache,
                 n_steps: int) -> tuple[jax.Array, KVCache]:
    """``n_steps`` fused greedy decode steps in ONE dispatch — one dispatch
    + one ``4·n_steps``-byte transfer per CHUNK instead of per token. Output
    is bit-identical to ``n_steps`` single greedy_step calls (greedy is
    deterministic); the caller truncates at EOS — tokens past it are
    discarded work, not divergence. ``token: [B]`` seeds the chunk."""
    return scan_decode(
        lambda t, p, kv: greedy_step(params, cfg, t, p, kv),
        token, start_pos, kv, n_steps)


def sampled_steps(params: Params, cfg: ModelConfig, token: jax.Array,
                  start_pos: jax.Array, kv: KVCache, temperature: jax.Array,
                  topp: jax.Array, coins: jax.Array,
                  n_steps: int) -> tuple[jax.Array, KVCache]:
    """The temperature>0 twin of :func:`greedy_steps`: ``coins [n_steps]``
    are the host xorshift draws for the whole chunk (the host rewinds its
    RNG to the number of tokens actually kept after EOS truncation, so the
    stream stays bit-identical to single-step decode).

    Also the RAGGED chunked step for batched serving (BatchedGenerator
    .step_chunk): everything broadcasts over rows — ``token/start_pos [B]``,
    vector ``temperature/topp [B]`` (temp<=0 rows take argmax), and ``coins
    [n_steps, B]`` (scan consumes axis 0) — so K fused steps run over the
    whole slot pool in one dispatch."""
    return scan_decode(
        lambda t, p, kv, c: sampled_step(params, cfg, t, p, kv,
                                         temperature, topp, c),
        token, start_pos, kv, n_steps, coins=coins)


def sampled_step(params: Params, cfg: ModelConfig, tokens: jax.Array,
                 start_pos: jax.Array, kv: KVCache, temperature: jax.Array,
                 topp: jax.Array, coin: jax.Array) -> tuple[jax.Array, KVCache]:
    """Fused forward + temperature/top-p sample of the last position — the
    temperature>0 twin of :func:`greedy_step`: one dispatch and a 4-byte
    transfer per sampled token instead of a vocab-row download (reference
    samples on host after the logits gather, src/tokenizer.cpp:480-510).
    ``temperature``/``topp``/``coin`` are traced f32 scalars (the host steps
    its xorshift* RNG and passes the coin in), so per-request sampling knobs
    never trigger a recompile."""
    from ..ops.sampling import sampled_token

    logits, kv = forward(params, cfg, tokens, start_pos, kv)
    return sampled_token(logits[:, -1, :], temperature, topp, coin), kv


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            start_pos: jax.Array, kv: KVCache) -> tuple[jax.Array, KVCache]:
    """Full forward: ``tokens [B, T]`` at absolute ``start_pos`` → logits.

    Returns float32 logits ``[B, T, vocab]`` and the updated cache. Jittable;
    ``start_pos`` is a traced scalar (all rows at one position) or a ``[B]``
    vector — per-row positions for ragged batched serving
    (runtime/serving.py), where each slot of the batch is its own sequence
    at its own depth. One compilation per ``T`` either way.
    """
    start_pos = jnp.asarray(start_pos, dtype=jnp.int32)
    ragged = start_pos.ndim > 0
    # numerics observatory taps (runtime/numerics): a TRACE-TIME flag, so
    # the default (off) trace is byte-identical — no tap code exists in it
    collect = _numerics.taps_active()
    plan = _current_plan()
    if plan is not None and plan.axis_size("pp") > 1:
        if collect:
            # the manual pp schedule owns its own shard_map region; tap
            # stats can't thread through it — fail at trace time rather
            # than silently returning an empty pytree
            raise ValueError("numerics taps are unsupported under "
                             "pipeline parallelism (pp > 1)")
        # pipeline parallelism: layer stack sharded over pp, stages hand the
        # activation along the ring (parallel/pipeline.py — new capability).
        # Ragged [B] start_pos (batched serving) rides along: each stage's
        # _layer_step gets the per-row depths.
        from ..parallel.pipeline import pp_forward, pp_manual_supported

        if pp_manual_supported(plan):
            return pp_forward(plan, cfg, params, tokens, start_pos, kv)
        # mixed pp mesh on a jax whose partial-auto shard_map is broken
        # (see pp_manual_supported): fall through to the auto-sharded
        # body — XLA derives the stage transfers from the layer-stack
        # sharding, value-identical to the manual schedule

    B, T = tokens.shape
    x = params.embedding[tokens].astype(cfg.compute_dtype)
    x = constrain(x, "batch", None, None)

    cos, sin = build_rope_cache(cfg)
    arange = jnp.arange(T, dtype=jnp.int32)[None, :]
    positions = (start_pos[:, None] if ragged else start_pos) + arange
    positions = jnp.broadcast_to(positions, (B, T))

    def body(carry, xs):
        x = carry
        lp, k_l, v_l = xs
        if cfg.offload:
            # weights stream host → device per layer; XLA prefetches the next
            # layer's transfer while this layer computes (cfg.offload docs)
            lp = jax.device_put(lp, jax.memory.Space.Device)
        if collect:
            x, k_l, v_l, st = _layer_step(cfg, x, lp, k_l, v_l, cos, sin,
                                          start_pos, positions, taps=True)
            return x, (k_l, v_l, st)
        x, k_l, v_l = _layer_step(cfg, x, lp, k_l, v_l, cos, sin,
                                  start_pos, positions)
        return x, (k_l, v_l)

    # scan over the stacked layer axis; caches ride along as per-layer xs/ys.
    # DLLAMA_TPU_SCAN_UNROLL (default 1) trades program size for fusion
    # across layer boundaries — the round-4 decode profile showed ~0.9 ms of
    # per-step loop overhead beyond the matmuls on the 1b shape. Part of the
    # multihost cluster fingerprint (different unroll = different program).
    unroll = int(os.environ.get("DLLAMA_TPU_SCAN_UNROLL", "1"))
    x, ys = jax.lax.scan(body, x, (params.layers, kv.k, kv.v),
                         unroll=max(1, unroll))
    if collect:
        new_k, new_v, layer_taps = ys  # stacked [L] leaves per site
    else:
        new_k, new_v = ys

    x = rms_norm(x, params.final_norm, cfg.norm_epsilon)
    final_stat = _tap_stat(x) if collect else None
    if cfg.sync_q80:  # final cast before the logits matmul (llm.cpp:445-486)
        x = fake_quant_q80(x)
    logits = linear(x, params.logits, out_axis="vocab").astype(jnp.float32)
    logits = constrain(logits, "batch", None, "vocab")
    if collect:
        taps = dict(layer_taps)
        taps["final_norm"] = final_stat
        taps["logits"] = _tap_stat(logits)
        return logits, KVCache(k=new_k, v=new_v), taps
    return logits, KVCache(k=new_k, v=new_v)


def forward_with_taps(params: Params, cfg: ModelConfig, tokens: jax.Array,
                      start_pos: jax.Array, kv: KVCache):
    """:func:`forward` with the numerics observatory's activation taps
    collected: returns ``((logits, taps), kv)`` where ``taps`` is the
    per-site stats pytree (``attn_out``/``mlp_out`` carry stacked ``[L]``
    leaves from the layer scan; ``final_norm``/``logits`` scalars — see
    :func:`_tap_stat`). A separate entry point (not a flag argument) so
    the plain program's trace stays byte-identical and the tapped one is
    only ever jitted when an engine opts in (``--numerics-taps``)."""
    with _numerics.collecting_taps():
        logits, kv, taps = forward(params, cfg, tokens, start_pos, kv)
    return (logits, taps), kv


def nll_from_logits(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Fused log-softmax-gather: per-position negative log-likelihood.

    ``logits [B, T, vocab]`` (float32), ``targets [B, T]`` int32 →
    ``nll [B, T]`` float32 where ``nll[b, t] = logsumexp(logits[b, t]) -
    logits[b, t, targets[b, t]]`` (always >= 0). The reduction is the
    whole point: jitted as the epilogue of :func:`prefill_nll`, the
    program's output is ``[B, T]``, so full-vocab logits for a long eval
    chunk never round-trip through HBM as a program result the host then
    downloads — the quality observatory scores 8k-token sequences at
    prefill bandwidth.
    """
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1))
    picked = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return lse - picked


def prefill_nll(params: Params, cfg: ModelConfig, tokens: jax.Array,
                targets: jax.Array, start_pos: jax.Array,
                kv: KVCache) -> tuple[jax.Array, KVCache]:
    """Teacher-forced prefill twin of :func:`forward` for the quality
    observatory (runtime/evalharness.py): same body, but the epilogue is
    the fused :func:`nll_from_logits` reduction instead of returning
    full-vocab logits. ``tokens [B, T]`` at ``start_pos`` with next-token
    ``targets [B, T]`` → per-position ``nll [B, T]`` float32 plus the
    updated cache, so an eval sequence's chunks double as its prefill.
    Padding rows (token 0 / target 0 past the chunk's valid length)
    compute garbage NLL the caller slices off — exactly the padding
    discipline of the serving prefill chunks, which is what makes the
    batched path bit-identical to the engine oracle.
    """
    logits, kv = forward(params, cfg, tokens, start_pos, kv)
    nll = constrain(nll_from_logits(logits, targets), "batch", None)
    return nll, kv


# ---------------------------------------------------------------------------
# Guarded decode steps — the non-finite tripwire (runtime/numerics)
# ---------------------------------------------------------------------------
#
# Every engine/serving decode dispatch runs a *_guarded twin of the fused
# step: same math, same program shape, plus (a) an in-graph poison selector
# (a traced f32 scalar driven by the `logits` failpoint — 0.0 in
# production, so arming chaos never recompiles) and (b) a fused per-row
# count of non-finite decode-step logits returned alongside the picked
# token. The raw steps above keep their signatures for bench.py and the
# parity tests; the guarded ones are what the engine jits (under the same
# program names, so the compile ledger's view is unchanged).


def _poison_logits(logits: jax.Array, poison: jax.Array) -> jax.Array:
    """Inject the failpoint's poison into the logits in-graph: 0 = clean
    passthrough, 1 = NaN, 2 = +Inf (numerics.POISON_CODES). Codes >= 3
    belong to the ``wire`` failpoint site (numerics.WIRE_POISON_CODES,
    injected into the ring collectives' shipped partials by
    parallel/qcollectives) and pass through clean here."""
    val = jnp.where(poison >= 2.0, jnp.float32(jnp.inf),
                    jnp.float32(jnp.nan))
    hit = jnp.logical_and(poison > 0.0, poison < 3.0)
    return jnp.where(hit, val.astype(logits.dtype), logits)


def _guarded_forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
                     start_pos: jax.Array, kv, poison: jax.Array,
                     fwd=None):
    """The guarded decode programs' forward: runs under
    ``wire_poison_scope`` so the overlapped wire collectives (when the
    trace contains them) carry the SAME traced poison scalar the logits
    site uses — codes 1-2 poison logits, 3-4 poison this device's shipped
    ring partial (batch row 0 only). One traced selector, so arming either
    chaos site never recompiles. Unguarded programs (prefill, bench paths)
    never enter the scope and trace no injection code at all."""
    from ..parallel.qcollectives import wire_poison_scope

    with wire_poison_scope(poison):
        return (fwd or forward)(params, cfg, tokens, start_pos, kv)


def _nonfinite_rows(logits: jax.Array) -> jax.Array:
    """Per-row count of non-finite lanes: ``[B, ...] -> [B] int32``."""
    bad = jnp.logical_not(jnp.isfinite(logits)).astype(jnp.int32)
    return jnp.sum(bad, axis=tuple(range(1, logits.ndim)))


def greedy_step_guarded(params: Params, cfg: ModelConfig, tokens: jax.Array,
                        start_pos: jax.Array, kv: KVCache,
                        poison: jax.Array):
    """:func:`greedy_step` + tripwire: returns ``((token, nonfinite), kv)``
    where ``nonfinite [B]`` counts non-finite lanes of the decode-step
    logits — the one row every emitted token is derived from."""
    logits, kv = _guarded_forward(params, cfg, tokens, start_pos, kv, poison)
    last = _poison_logits(logits[:, -1, :], poison)
    tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    return (tok, _nonfinite_rows(last)), kv


def sampled_step_guarded(params: Params, cfg: ModelConfig, tokens: jax.Array,
                         start_pos: jax.Array, kv: KVCache,
                         temperature: jax.Array, topp: jax.Array,
                         coin: jax.Array, poison: jax.Array):
    """:func:`sampled_step` + tripwire (also the ragged batched-serving
    step: everything broadcasts over rows, ``nonfinite [B]`` is per
    slot so a poisoned request can be failed without touching the rest
    of the batch)."""
    from ..ops.sampling import sampled_token

    logits, kv = _guarded_forward(params, cfg, tokens, start_pos, kv, poison)
    last = _poison_logits(logits[:, -1, :], poison)
    return (sampled_token(last, temperature, topp, coin),
            _nonfinite_rows(last)), kv


def _scan_decode_guarded(step1, token: jax.Array, start_pos: jax.Array,
                         kv: KVCache, n_steps: int,
                         coins: jax.Array | None = None):
    """Guarded twin of :func:`scan_decode`: ``step1`` returns
    ``((tok, nf), kv)`` and the per-row non-finite counts accumulate over
    the chunk's scan carry — one fused count per dispatch, exactly like
    the tokens themselves."""

    def body(carry, xs):
        token, kv, nf = carry
        if coins is None:
            (nxt, nf_i), kv = step1(token[:, None], start_pos + xs, kv)
        else:
            i, coin = xs
            (nxt, nf_i), kv = step1(token[:, None], start_pos + i, kv, coin)
        return (nxt, kv, nf + nf_i), nxt

    xs = jnp.arange(n_steps, dtype=jnp.int32)
    nf0 = jnp.zeros(token.shape, dtype=jnp.int32)
    (_, kv, nf), toks = jax.lax.scan(
        body, (token, kv, nf0), xs if coins is None else (xs, coins))
    return (jnp.moveaxis(toks, 0, 1), nf), kv  # ([B, n_steps], [B])


def greedy_steps_guarded(params: Params, cfg: ModelConfig, token: jax.Array,
                         start_pos: jax.Array, kv: KVCache, n_steps: int,
                         poison: jax.Array):
    """:func:`greedy_steps` + tripwire: ``((tokens, nonfinite), kv)``."""
    return _scan_decode_guarded(
        lambda t, p, kv: greedy_step_guarded(params, cfg, t, p, kv, poison),
        token, start_pos, kv, n_steps)


def sampled_steps_guarded(params: Params, cfg: ModelConfig, token: jax.Array,
                          start_pos: jax.Array, kv: KVCache,
                          temperature: jax.Array, topp: jax.Array,
                          coins: jax.Array, n_steps: int,
                          poison: jax.Array):
    """:func:`sampled_steps` + tripwire (also the ragged chunked step for
    batched serving, like its unguarded twin)."""
    return _scan_decode_guarded(
        lambda t, p, kv, c: sampled_step_guarded(params, cfg, t, p, kv,
                                                 temperature, topp, c,
                                                 poison),
        token, start_pos, kv, n_steps, coins=coins)


def verify_step_guarded(params: Params, cfg: ModelConfig, tokens: jax.Array,
                        start_pos: jax.Array, kv: KVCache,
                        poison: jax.Array):
    """:func:`verify_step` + tripwire over all K+1 verify positions (every
    one of them can become an emitted token): ``((n_acc, preds, nf), kv)``."""
    logits, kv = _guarded_forward(params, cfg, tokens, start_pos, kv, poison)
    logits = _poison_logits(logits, poison)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
    ok = (tokens[:, 1:] == preds[:, :-1]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(ok, axis=-1), axis=-1)
    return (n_acc, preds, _nonfinite_rows(logits)), kv


def ragged_verify_step_guarded(params: Params, cfg: ModelConfig,
                               tokens: jax.Array, pos_vec: jax.Array,
                               kv: KVCache, temps: jax.Array,
                               topps: jax.Array, coins: jax.Array,
                               poison: jax.Array):
    """:func:`ragged_verify_step` + tripwire: ``((n_acc, preds, nf), kv)``
    with per-row counts so batched serving fails only the poisoned
    slot."""
    from ..ops.sampling import sampled_token

    logits, kv = _guarded_forward(params, cfg, tokens, pos_vec, kv, poison)
    logits = _poison_logits(logits, poison)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
    ok = (tokens[:, 1:] == preds[:, :-1]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(ok, axis=-1), axis=-1)
    greedy_row = jnp.asarray(temps) <= 0.0
    n_acc = jnp.where(greedy_row, n_acc, 0)
    first = sampled_token(logits[:, 0], temps, topps, coins)
    preds = preds.at[:, 0].set(first)
    return (n_acc, preds, _nonfinite_rows(logits)), kv


# ---------------------------------------------------------------------------
# Paged program family — block-table KV (runtime/kvblocks.py)
# ---------------------------------------------------------------------------
#
# The paged twins of the ragged serving programs: KV lives in a block pool
# [L, n_blocks, n_kv, block_size, hd] and every row of the batch addresses
# its context through a block table. Shapes are static per pool geometry
# (n_blocks, block_size, batch width, table width), so the whole family
# jits once per geometry and the compile ledger stays quiet across
# admissions/retirements — the continuous-batching requirement.


def paged_forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
                  pos_vec: jax.Array, pkv, tables: jax.Array,
                  write_lens: jax.Array | None = None):
    """Full forward over the paged pool: ``tokens [B, T]`` at per-row
    ``pos_vec [B]`` with block ``tables [B, max_blocks]``. Returns float32
    logits ``[B, T, vocab]`` and the updated pool (a
    :class:`~dllama_tpu.runtime.kvblocks.PagedKVCache`). Always ragged —
    the paged path exists for continuous batching only. ``write_lens``
    (speculative verify: per-row valid input width minus one, i.e. the
    row's draft length) masks KV writes for lanes past it to the null
    block — see :func:`_paged_layer_step`."""
    from ..runtime.kvblocks import PagedKVCache

    if _numerics.taps_active():
        raise ValueError("numerics taps are unsupported on the paged KV "
                         "path (use the dense slot pool for tap sessions)")
    plan = _current_plan()
    if plan is not None and plan.axis_size("pp") > 1:
        raise ValueError("paged KV is unsupported under pipeline "
                         "parallelism (pp > 1)")
    pos_vec = jnp.asarray(pos_vec, dtype=jnp.int32)
    B, T = tokens.shape
    x = params.embedding[tokens].astype(cfg.compute_dtype)
    x = constrain(x, "batch", None, None)

    cos, sin = build_rope_cache(cfg)
    arange = jnp.arange(T, dtype=jnp.int32)[None, :]
    positions = jnp.broadcast_to(pos_vec[:, None] + arange, (B, T))

    def body(carry, xs):
        x = carry
        lp, k_l, v_l = xs
        if cfg.offload:
            lp = jax.device_put(lp, jax.memory.Space.Device)
        x, k_l, v_l = _paged_layer_step(cfg, x, lp, k_l, v_l, cos, sin,
                                        positions, tables, write_lens)
        return x, (k_l, v_l)

    unroll = int(os.environ.get("DLLAMA_TPU_SCAN_UNROLL", "1"))
    x, (new_k, new_v) = jax.lax.scan(body, x, (params.layers, pkv.k, pkv.v),
                                     unroll=max(1, unroll))
    x = rms_norm(x, params.final_norm, cfg.norm_epsilon)
    if cfg.sync_q80:
        x = fake_quant_q80(x)
    logits = linear(x, params.logits, out_axis="vocab").astype(jnp.float32)
    logits = constrain(logits, "batch", None, "vocab")
    return logits, PagedKVCache(k=new_k, v=new_v)


def paged_sampled_step_guarded(params: Params, cfg: ModelConfig,
                               tokens: jax.Array, pos_vec: jax.Array,
                               pkv, tables: jax.Array, temps: jax.Array,
                               topps: jax.Array, coins: jax.Array,
                               poison: jax.Array):
    """The paged ragged decode step + non-finite tripwire — the block-table
    twin of :func:`sampled_step_guarded`: one dispatch samples every row
    (temp <= 0 rows take argmax), ``nonfinite [B]`` is per row so a
    poisoned request fails without touching the rest of the batch.
    Returns ``((token, nonfinite), pkv)``."""
    from ..ops.sampling import sampled_token
    from ..parallel.qcollectives import wire_poison_scope

    with wire_poison_scope(poison):
        logits, pkv = paged_forward(params, cfg, tokens, pos_vec, pkv, tables)
    last = _poison_logits(logits[:, -1, :], poison)
    return (sampled_token(last, temps, topps, coins),
            _nonfinite_rows(last)), pkv


def paged_verify_step(params: Params, cfg: ModelConfig, tokens: jax.Array,
                      pos_vec: jax.Array, pkv, tables: jax.Array,
                      lens: jax.Array, temps: jax.Array, topps: jax.Array,
                      acoins: jax.Array, fcoins: jax.Array):
    """The paged speculative verify step — the block-table twin of
    :func:`ragged_verify_step`, widened to speculative *sampling*.

    One forward over ``tokens [B, K+1]`` (each row: its committed next
    token followed by its proposer's drafts, padded past the row's
    ``lens [B]`` draft length) at per-row ``pos_vec``, KV scattered
    through the block ``tables`` with writes masked past ``lens``
    (:func:`paged_forward` ``write_lens`` — the host only allocates
    blocks covering ``pos..pos+lens``). The logits epilogue is
    :func:`runtime.speculative.spec_decide`: greedy rows accept the
    longest model-matching draft prefix exactly as the dense path does;
    sampled rows run rejection-sampling acceptance with the residual
    resample / ``sampled_token`` bonus, so their emitted distribution is
    exactly the non-speculative sampling distribution. Returns
    ``(n_acc [B], out [B, K+1], pkv)``; the caller emits
    ``out[b, : n_acc[b] + 1]``.

    KV safety is the verify-step argument one level up: every write
    lands at/above the row's committed ``pos`` in refcount-1 blocks the
    slot owns (shared prefix blocks are never a write target —
    ``__debug__``-asserted by the generator), so rejected lanes need no
    device rollback: the table/pos bookkeeping alone rolls them back,
    and the next dispatch's writes start exactly where the stale region
    starts. Jitted once per pool geometry (``K+1``, table width, batch
    width are static; ``lens``/coins/knobs traced), so varying per-slot
    draft lengths and admit/retire churn never retrace."""
    from ..runtime.speculative import spec_decide

    logits, pkv = paged_forward(params, cfg, tokens, pos_vec, pkv, tables,
                                write_lens=lens)
    n_acc, out = spec_decide(logits, tokens, lens, temps, topps,
                             acoins, fcoins)
    return n_acc, out, pkv


def paged_verify_step_guarded(params: Params, cfg: ModelConfig,
                              tokens: jax.Array, pos_vec: jax.Array,
                              pkv, tables: jax.Array, lens: jax.Array,
                              temps: jax.Array, topps: jax.Array,
                              acoins: jax.Array, fcoins: jax.Array,
                              poison: jax.Array):
    """:func:`paged_verify_step` + tripwire over all K+1 verify positions
    (every one can become an emitted token): ``((n_acc, out, nf), pkv)``
    with per-row non-finite counts so batched serving fails only the
    poisoned slot."""
    from ..parallel.qcollectives import wire_poison_scope
    from ..runtime.speculative import spec_decide

    with wire_poison_scope(poison):
        logits, pkv = paged_forward(params, cfg, tokens, pos_vec, pkv,
                                    tables, write_lens=lens)
    logits = _poison_logits(logits, poison)
    n_acc, out = spec_decide(logits, tokens, lens, temps, topps,
                             acoins, fcoins)
    return (n_acc, out, _nonfinite_rows(logits)), pkv


def gather_kv_blocks(pkv, ids: jax.Array):
    """Device side of a KV-tier SPILL (runtime/kvblocks.HostKVMirror):
    gather ``len(ids)`` physical blocks out of the pool as one contiguous
    chunk ``(k, v)`` each ``[L, K, n_kv, bs, hd]`` — ONE batched read per
    spill, then a single ``device_put`` moves the chunk to pinned host
    memory. ``ids`` is traced (fixed K = kvblocks.SPILL_BATCH, short
    batches padded with the null block), so tier pressure never retraces.
    Plan-independent data movement — jitted raw at the call site, same
    argument as PagedGenerator's take/put/copy programs."""
    return pkv.k[:, ids], pkv.v[:, ids]


def scatter_kv_blocks(pkv, chunk_k: jax.Array, chunk_v: jax.Array,
                      ids: jax.Array):
    """Device side of a KV-tier PAGE-IN: scatter a host chunk (moved back
    device-side by ``device_put``) into the pool at physical blocks
    ``ids``. Lanes the page-in does not want target the null block (id 0)
    — its contents are value-invisible garbage by the pool's contract, so
    a partial chunk restore is the same one program. Returns the updated
    pool (donated at the jit wrapper)."""
    from ..runtime.kvblocks import PagedKVCache

    return PagedKVCache(k=pkv.k.at[:, ids].set(chunk_k.astype(pkv.k.dtype)),
                        v=pkv.v.at[:, ids].set(chunk_v.astype(pkv.v.dtype)))


# ---------------------------------------------------------------------------
# Parameter construction
# ---------------------------------------------------------------------------


def _stack_weights(ws: list[Any]) -> Any:
    if isinstance(ws[0], QuantizedWeight):
        return QuantizedWeight(
            scales=jnp.stack([w.scales for w in ws]),
            codes=jnp.stack([w.codes for w in ws]),
        )
    return jnp.stack(ws)


def load_params_from_mfile(mf: ModelFile, cfg: ModelConfig,
                           weight_mode: str = "auto", plan=None) -> Params:
    """Build device params from a .m file via the streaming loader.

    ``weight_mode``: ``"auto"`` keeps Q40 files quantized on device (planes),
    ``"f32"``/``"bf16"`` dequantize to dense. With ``plan`` the params come
    back fully sharded — each device shard's bytes are read directly from the
    mmap (runtime.weights), replacing the reference's root-to-worker weight
    streaming (NnRootWeightLoader, SURVEY.md §2 #12) with bounded host memory.
    """
    from ..runtime.weights import load_params

    return load_params(mf, cfg, weight_mode, plan)


def init_random_params(cfg: ModelConfig, seed: int = 0, scale: float = 0.02,
                       quantized: bool = False, dtype=jnp.float32) -> Params:
    """Random params for tests/benchmarks (shape-identical to a loaded model)."""
    rng = np.random.default_rng(seed)

    def rand(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    def mk(out, in_) -> Weight:
        w = rand(cfg.n_layers, out, in_)
        if quantized:
            return _stack_weights([quantize_weight_q40(w[l]) for l in range(cfg.n_layers)])
        return jnp.asarray(w, dtype=dtype)

    def mk_experts(out, in_) -> Weight:
        if quantized:
            w = rand(cfg.n_layers, cfg.n_experts, out, in_)
            return _stack_weights([
                _stack_weights([quantize_weight_q40(w[l, e])
                                for e in range(cfg.n_experts)])
                for l in range(cfg.n_layers)])
        # dense experts store IN-major (ragged_dot rhs layout)
        return jnp.asarray(rand(cfg.n_layers, cfg.n_experts, in_, out),
                           dtype=cfg.compute_dtype)

    qwen3 = cfg.arch == ArchType.QWEN3
    moe = cfg.is_moe
    layers = LayerParams(
        wq=mk(cfg.q_dim, cfg.dim),
        wk=mk(cfg.kv_dim, cfg.dim),
        wv=mk(cfg.kv_dim, cfg.dim),
        wo=mk(cfg.dim, cfg.q_dim),
        w1=None if moe else mk(cfg.hidden_dim, cfg.dim),
        w2=None if moe else mk(cfg.dim, cfg.hidden_dim),
        w3=None if moe else mk(cfg.hidden_dim, cfg.dim),
        norm_att=jnp.asarray(1.0 + rand(cfg.n_layers, cfg.dim)),
        norm_ffn=jnp.asarray(1.0 + rand(cfg.n_layers, cfg.dim)),
        norm_q=jnp.asarray(1.0 + rand(cfg.n_layers, cfg.head_dim)) if qwen3 else None,
        norm_k=jnp.asarray(1.0 + rand(cfg.n_layers, cfg.head_dim)) if qwen3 else None,
        moe_gate=(jnp.asarray(rand(cfg.n_layers, cfg.n_experts, cfg.dim))
                  if moe else None),
        # in-major expert layout (see LayerParams); quantized=True mirrors
        # the loader's Q40 expert planes ([L, E]-stacked QuantizedWeight)
        we1=mk_experts(cfg.hidden_dim, cfg.dim) if moe else None,
        we2=mk_experts(cfg.dim, cfg.hidden_dim) if moe else None,
        we3=mk_experts(cfg.hidden_dim, cfg.dim) if moe else None,
    )
    logits = rand(cfg.vocab_size, cfg.dim)
    return Params(
        embedding=jnp.asarray(rand(cfg.vocab_size, cfg.dim)),
        layers=layers,
        final_norm=jnp.asarray(1.0 + rand(cfg.dim)),
        logits=(quantize_weight_q40(logits) if quantized
                else jnp.asarray(logits, dtype=dtype)),
    )
