"""Functional transformer forward for Llama 2/3/3.x and Qwen3.

This replaces the reference's per-node op-graph builder (reference:
buildLlmNet, src/llm.cpp:142-490) with a single SPMD program: the graph that
the reference assembles as [merge_add, inv_rms, rms_norm, cast, matmul_q/k/v,
(qwen3 q/k norms), rope, shift, multihead_att, cast, matmul_wo, cast, SYNC] +
[merge_add, inv_rms, rms_norm, cast, w1/w3, silu, mul, cast, w2, cast, SYNC]
per layer (llm.cpp:226-443) is expressed directly in jnp; tensor-parallel
synchronization (the two all-reduces per layer) is carried by sharding
annotations + XLA collectives instead of explicit SYNC steps.

Design choices (TPU-first, not a translation):

* **Stacked layer parameters + ``lax.scan``** — one compiled layer body
  regardless of depth; keeps compile time O(1) in ``n_layers`` and lets XLA
  pipeline HBM prefetch of the next layer's weights.
* Batch dimension is ``[B, T]`` *sequences × positions* — the reference's
  positions-as-batch prefill (nBatches, SURVEY.md §2.2) is the ``B=1`` case.
* Activations carry logical axis names via
  :func:`dllama_tpu.parallel.constrain` so the same code runs single-chip or
  sharded over any mesh.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.mfile import ArchType, HiddenAct, ModelFile, RopeType
from ..formats.quants import Q40
from ..ops import flash_attention as _fa
from ..ops.attention import attention
from ..ops.flash_attention import flash_attention
from ..ops.linear import (
    QuantizedWeight,
    Weight,
    fake_quant_q80,
    linear,
    quantize_weight_q40,
)
from ..ops.norms import rms_norm, rms_norm_per_head
from ..parallel.api import constrain
from ..parallel.api import current_plan as _current_plan
from ..runtime.kvcache import KVCache, update_layer
from .config import ModelConfig
from .rope import apply_rope, build_rope_cache


class LayerParams(NamedTuple):
    """Per-layer weights; every leaf carries a leading ``[n_layers]`` axis."""

    wq: Weight  # [L, q_dim, dim]
    wk: Weight  # [L, kv_dim, dim]
    wv: Weight  # [L, kv_dim, dim]
    wo: Weight  # [L, dim, q_dim]
    w1: Weight | None  # [L, hidden_dim, dim]   (gate; None for MoE layers)
    w2: Weight | None  # [L, dim, hidden_dim]   (down)
    w3: Weight | None  # [L, hidden_dim, dim]   (up)
    norm_att: jax.Array  # [L, dim]
    norm_ffn: jax.Array  # [L, dim]
    norm_q: jax.Array | None  # [L, head_dim] (qwen3) or None
    norm_k: jax.Array | None
    # MoE (None for dense models). Expert weights are kept dense (compute
    # dtype): the quantized Pallas matmul path doesn't cover the stacked
    # expert axis yet.
    moe_gate: jax.Array | None = None  # [L, E, dim] router
    we1: jax.Array | None = None       # [L, E, hidden_dim, dim] (gate)
    we2: jax.Array | None = None       # [L, E, dim, hidden_dim] (down)
    we3: jax.Array | None = None       # [L, E, hidden_dim, dim] (up)


class Params(NamedTuple):
    embedding: jax.Array  # [vocab, dim]
    layers: LayerParams
    final_norm: jax.Array  # [dim]
    logits: Weight  # [vocab, dim]


def _use_flash(cfg: ModelConfig, q_shape, kv_shape) -> bool:
    """Trace-time choice of the single-device attention kernel. Under a mesh
    plan the auto-sharder cannot partition a pallas_call — the TP path wraps
    the kernel in shard_map (flash_attention_sharded) and the SP path has its
    own kernels (parallel/ring.py)."""
    from ..parallel.api import current_plan

    if cfg.attn_impl not in ("auto", "xla", "flash"):
        raise ValueError(f"attn_impl must be auto|xla|flash, got {cfg.attn_impl!r}")
    if cfg.attn_impl == "xla":
        return False
    n_kv, s = kv_shape[1], kv_shape[2]
    ok = _fa.supports(q_shape, n_kv, s)
    if cfg.attn_impl == "flash":
        if not ok:
            raise ValueError(f"flash attention unsupported for q={q_shape}, S={s}")
        return current_plan() is None
    return ok and _fa.default_enabled() and current_plan() is None


def _sharded_flash(cfg: ModelConfig, plan, q, k_cache, v_cache, start_pos):
    """TP-path Pallas attention via shard_map; None → caller uses the oracle.

    ``attn_impl='flash'`` forces it (interpret mode off-TPU, for tests);
    ``'auto'`` enables it on TPU backends only."""
    if cfg.attn_impl == "xla":
        return None
    force = cfg.attn_impl == "flash"
    if not force and not _fa.default_enabled():
        return None
    return _fa.flash_attention_sharded(
        plan, q, k_cache, v_cache, start_pos, cfg.head_dim,
        interpret=force and not _fa.default_enabled())


def _hidden_act(cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.hidden_act == HiddenAct.SILU:
        return jax.nn.silu(x)
    # tanh-approx gelu (reference: gelu_F32, nn-cpu-ops.cpp:1133-1142)
    return jax.nn.gelu(x, approximate=True)


def _moe_ffn(cfg: ModelConfig, h: jax.Array, lp: LayerParams) -> jax.Array:
    """Mixture-of-experts SwiGLU FFN — new capability (the reference parses
    N_EXPERTS but its graph builder never emits expert ops, SURVEY.md §2.2).

    Router: softmax over all expert logits, top-k, then either renormalize
    the selected weights to sum to 1 (cfg.moe_norm_topk — Mixtral semantics,
    and note renormalizing is identical to softmaxing the selected logits)
    or keep the raw probabilities (Qwen3-MoE with HF norm_topk_prob false).
    Compute is dense over the expert axis — every expert runs on every token,
    weighted by the (sparse) gate — which is exact and shards cleanly: with
    "experts" mapped to the ``ep`` mesh axis each device computes only its
    local experts and XLA psums the combine. A grouped/megablocks-style
    sparse matmul is a planned optimization.
    """
    E, k = cfg.n_experts, cfg.n_active_experts
    logits = jnp.einsum("btd,ed->bte", h.astype(jnp.float32),
                        lp.moe_gate.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    top, idx = jax.lax.top_k(probs, k)
    if cfg.moe_norm_topk:
        weights = top / jnp.sum(top, axis=-1, keepdims=True)
    else:
        weights = top
    one_hot = jax.nn.one_hot(idx, E, dtype=jnp.float32)      # [B,T,k,E]
    gates = jnp.einsum("btke,btk->bte", one_hot, weights)    # sparse rows
    gates = constrain(gates, "batch", None, "experts")

    ht = h.astype(cfg.compute_dtype)
    h1 = jnp.einsum("btd,ehd->bteh", ht, lp.we1)
    h3 = jnp.einsum("btd,ehd->bteh", ht, lp.we3)
    a = _hidden_act(cfg, h1) * h3
    a = constrain(a, "batch", None, "experts", "hidden")
    y = jnp.einsum("bteh,edh,bte->btd", a, lp.we2,
                   gates.astype(cfg.compute_dtype))
    return y.astype(h.dtype)


def _layer_step(cfg: ModelConfig, x: jax.Array, lp: LayerParams,
                k_cache: jax.Array, v_cache: jax.Array,
                cos: jax.Array, sin: jax.Array, start_pos: jax.Array,
                positions: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One transformer block. ``x: [B, T, dim]``; caches are head-major
    ``[B, n_kv, S, hd]`` (see runtime.kvcache)."""
    B, T, _ = x.shape

    # Q80 sync-parity: fake-quantize at the reference's cast points — matmul
    # inputs (X→Q80 casts) and the partial-sum outputs that cross the wire
    # (ZQ pipe casts, llm.cpp:258-265, 360-365, 433-438).
    fq = fake_quant_q80 if cfg.sync_q80 else (lambda a: a)

    # -- attention half (reference att segment, llm.cpp:226-366) -----------
    h = fq(rms_norm(x, lp.norm_att, cfg.norm_epsilon))
    q = linear(h, lp.wq, out_axis="heads").reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = linear(h, lp.wk, out_axis="kv_heads").reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = linear(h, lp.wv, out_axis="kv_heads").reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    q = constrain(q, "batch", None, "heads", None)
    k = constrain(k, "batch", None, "kv_heads", None)
    v = constrain(v, "batch", None, "kv_heads", None)

    if cfg.uses_qk_norm:
        q = rms_norm_per_head(q, lp.norm_q, cfg.norm_epsilon)
        k = rms_norm_per_head(k, lp.norm_k, cfg.norm_epsilon)

    q = apply_rope(q, cos, sin, positions, cfg.rope_type)
    k = apply_rope(k, cos, sin, positions, cfg.rope_type)

    sp_res = None
    plan = _current_plan()
    if plan is not None and plan.axis_size("sp") > 1:
        from ..parallel.ring import sp_attention

        sp_res = sp_attention(plan, q, k_cache, v_cache, k, v, positions,
                              start_pos, cfg.head_dim)
    if sp_res is not None:
        att, k_cache, v_cache = sp_res
    else:
        k_cache, v_cache = update_layer(k_cache, v_cache, k, v, start_pos)
        att = (_sharded_flash(cfg, plan, q, k_cache, v_cache, start_pos)
               if plan is not None else None)
        if att is None:
            if _use_flash(cfg, q.shape, k_cache.shape):
                att = flash_attention(q, k_cache, v_cache, start_pos, cfg.head_dim)
            else:
                att = attention(q, k_cache, v_cache, positions, cfg.head_dim)
    att = constrain(att, "batch", None, "heads", None)
    x = x + fq(linear(fq(att.reshape(B, T, cfg.q_dim)), lp.wo, in_axis="heads"))
    x = constrain(x, "batch", None, None)

    # -- ffn half (reference ff segment, llm.cpp:369-439; MoE is new) ------
    h = fq(rms_norm(x, lp.norm_ffn, cfg.norm_epsilon))
    if cfg.is_moe:
        x = x + fq(_moe_ffn(cfg, h, lp))
    else:
        gate = _hidden_act(cfg, linear(h, lp.w1, out_axis="hidden"))
        up = linear(h, lp.w3, out_axis="hidden")
        hidden = constrain(fq(gate * up), "batch", None, "hidden")
        x = x + fq(linear(hidden, lp.w2, in_axis="hidden"))
    x = constrain(x, "batch", None, None)
    return x, k_cache, v_cache


def greedy_step(params: Params, cfg: ModelConfig, tokens: jax.Array,
                start_pos: jax.Array, kv: KVCache) -> tuple[jax.Array, KVCache]:
    """Fused forward + argmax of the last position — the single-dispatch
    greedy decode step (SURVEY.md §7.4 "single fused jitted step"). Shared by
    the engine's fast path and bench.py so the benchmark measures the
    production program."""
    logits, kv = forward(params, cfg, tokens, start_pos, kv)
    return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32), kv


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            start_pos: jax.Array, kv: KVCache) -> tuple[jax.Array, KVCache]:
    """Full forward: ``tokens [B, T]`` at absolute ``start_pos`` → logits.

    Returns float32 logits ``[B, T, vocab]`` and the updated cache. Jittable;
    ``start_pos`` is a traced scalar so prefill chunks and decode steps reuse
    one compilation per ``T``.
    """
    B, T = tokens.shape
    x = params.embedding[tokens].astype(cfg.compute_dtype)
    x = constrain(x, "batch", None, None)

    cos, sin = build_rope_cache(cfg)
    positions = start_pos + jnp.arange(T, dtype=jnp.int32)[None, :]
    positions = jnp.broadcast_to(positions, (B, T))

    def body(carry, xs):
        x = carry
        lp, k_l, v_l = xs
        x, k_l, v_l = _layer_step(cfg, x, lp, k_l, v_l, cos, sin,
                                  start_pos, positions)
        return x, (k_l, v_l)

    # scan over the stacked layer axis; caches ride along as per-layer xs/ys
    x, (new_k, new_v) = jax.lax.scan(body, x, (params.layers, kv.k, kv.v))

    x = rms_norm(x, params.final_norm, cfg.norm_epsilon)
    if cfg.sync_q80:  # final cast before the logits matmul (llm.cpp:445-486)
        x = fake_quant_q80(x)
    logits = linear(x, params.logits, out_axis="vocab").astype(jnp.float32)
    logits = constrain(logits, "batch", None, "vocab")
    return logits, KVCache(k=new_k, v=new_v)


# ---------------------------------------------------------------------------
# Parameter construction
# ---------------------------------------------------------------------------


def _stack_weights(ws: list[Any]) -> Any:
    if isinstance(ws[0], QuantizedWeight):
        return QuantizedWeight(
            scales=jnp.stack([w.scales for w in ws]),
            codes=jnp.stack([w.codes for w in ws]),
        )
    return jnp.stack(ws)


def load_params_from_mfile(mf: ModelFile, cfg: ModelConfig,
                           weight_mode: str = "auto") -> Params:
    """Build device params from a .m file.

    ``weight_mode``: ``"auto"`` keeps Q40 files quantized on device (planes),
    ``"f32"``/``"bf16"`` dequantize to dense. This replaces the reference's
    root-to-worker weight streaming (NnRootWeightLoader, SURVEY.md §2 #12):
    under SPMD the per-device shard transfer happens in ``jax.device_put``
    against the params' NamedShardings.
    """
    h = mf.header
    quantized = h.weight_type == Q40 and weight_mode == "auto"
    dense_dtype = jnp.bfloat16 if weight_mode == "bf16" else jnp.float32

    def matmul_weight(key: str) -> Weight:
        if quantized:
            # disk layout is out-major; device layout is K-major (QuantizedWeight);
            # the repack runs in native code when built (dllama_tpu/native)
            scales, codes = mf.tensor_q40_kmajor(key)
            return QuantizedWeight(scales=jnp.asarray(scales),
                                   codes=jnp.asarray(codes))
        return jnp.asarray(mf.tensor_f32(key), dtype=dense_dtype)

    def f32(key: str) -> jax.Array:
        return jnp.asarray(mf.tensor_f32(key))

    moe = h.n_experts > 0
    if moe and not mf.has_moe_router:
        raise ValueError(
            "MoE model file has no router tensors (written by the reference "
            "converter, which never emits block_moe_gate) — reconvert with "
            "python -m dllama_tpu.convert")

    def expert_stack(name: str) -> jax.Array:
        """[L, E, out, in] dense expert weights in compute dtype (cast
        per-tensor before stacking to keep host peak memory at the target
        dtype, not f32)."""
        # honor weight_mode like matmul_weight does (bf16 halves the footprint
        # of what is the bulk of an MoE checkpoint); "auto" follows compute dtype
        target = jnp.dtype(dense_dtype if weight_mode != "auto"
                           else cfg.compute_dtype)
        first = mf.tensor_f32(f"{name}.0.0")
        out = np.empty((h.n_layers, h.n_experts) + first.shape, dtype=target)
        for l in range(h.n_layers):
            for e in range(h.n_experts):
                out[l, e] = mf.tensor_f32(f"{name}.{l}.{e}")
        return jnp.asarray(out)

    layers = LayerParams(
        wq=_stack_weights([matmul_weight(f"block_matmul_q.{l}") for l in range(h.n_layers)]),
        wk=_stack_weights([matmul_weight(f"block_matmul_k.{l}") for l in range(h.n_layers)]),
        wv=_stack_weights([matmul_weight(f"block_matmul_v.{l}") for l in range(h.n_layers)]),
        wo=_stack_weights([matmul_weight(f"block_matmul_wo.{l}") for l in range(h.n_layers)]),
        w1=None if moe else _stack_weights(
            [matmul_weight(f"block_matmul_w1.{l}") for l in range(h.n_layers)]),
        w2=None if moe else _stack_weights(
            [matmul_weight(f"block_matmul_w2.{l}") for l in range(h.n_layers)]),
        w3=None if moe else _stack_weights(
            [matmul_weight(f"block_matmul_w3.{l}") for l in range(h.n_layers)]),
        norm_att=jnp.stack([f32(f"block_norm_0.{l}") for l in range(h.n_layers)]),
        norm_ffn=jnp.stack([f32(f"block_norm_1.{l}") for l in range(h.n_layers)]),
        norm_q=(jnp.stack([f32(f"block_norm_q.{l}") for l in range(h.n_layers)])
                if h.arch_type == ArchType.QWEN3 else None),
        norm_k=(jnp.stack([f32(f"block_norm_k.{l}") for l in range(h.n_layers)])
                if h.arch_type == ArchType.QWEN3 else None),
        moe_gate=(jnp.stack([f32(f"block_moe_gate.{l}") for l in range(h.n_layers)])
                  if moe else None),
        we1=expert_stack("block_expert_w1") if moe else None,
        we2=expert_stack("block_expert_w2") if moe else None,
        we3=expert_stack("block_expert_w3") if moe else None,
    )
    return Params(
        embedding=f32("embedding"),
        layers=layers,
        final_norm=f32("final_norm"),
        logits=matmul_weight("final_matmul_logits"),
    )


def init_random_params(cfg: ModelConfig, seed: int = 0, scale: float = 0.02,
                       quantized: bool = False, dtype=jnp.float32) -> Params:
    """Random params for tests/benchmarks (shape-identical to a loaded model)."""
    rng = np.random.default_rng(seed)

    def rand(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    def mk(out, in_) -> Weight:
        w = rand(cfg.n_layers, out, in_)
        if quantized:
            return _stack_weights([quantize_weight_q40(w[l]) for l in range(cfg.n_layers)])
        return jnp.asarray(w, dtype=dtype)

    qwen3 = cfg.arch == ArchType.QWEN3
    moe = cfg.is_moe
    layers = LayerParams(
        wq=mk(cfg.q_dim, cfg.dim),
        wk=mk(cfg.kv_dim, cfg.dim),
        wv=mk(cfg.kv_dim, cfg.dim),
        wo=mk(cfg.dim, cfg.q_dim),
        w1=None if moe else mk(cfg.hidden_dim, cfg.dim),
        w2=None if moe else mk(cfg.dim, cfg.hidden_dim),
        w3=None if moe else mk(cfg.hidden_dim, cfg.dim),
        norm_att=jnp.asarray(1.0 + rand(cfg.n_layers, cfg.dim)),
        norm_ffn=jnp.asarray(1.0 + rand(cfg.n_layers, cfg.dim)),
        norm_q=jnp.asarray(1.0 + rand(cfg.n_layers, cfg.head_dim)) if qwen3 else None,
        norm_k=jnp.asarray(1.0 + rand(cfg.n_layers, cfg.head_dim)) if qwen3 else None,
        moe_gate=(jnp.asarray(rand(cfg.n_layers, cfg.n_experts, cfg.dim))
                  if moe else None),
        we1=(jnp.asarray(rand(cfg.n_layers, cfg.n_experts, cfg.hidden_dim, cfg.dim),
                         dtype=cfg.compute_dtype) if moe else None),
        we2=(jnp.asarray(rand(cfg.n_layers, cfg.n_experts, cfg.dim, cfg.hidden_dim),
                         dtype=cfg.compute_dtype) if moe else None),
        we3=(jnp.asarray(rand(cfg.n_layers, cfg.n_experts, cfg.hidden_dim, cfg.dim),
                         dtype=cfg.compute_dtype) if moe else None),
    )
    logits = rand(cfg.vocab_size, cfg.dim)
    return Params(
        embedding=jnp.asarray(rand(cfg.vocab_size, cfg.dim)),
        layers=layers,
        final_norm=jnp.asarray(1.0 + rand(cfg.dim)),
        logits=(quantize_weight_q40(logits) if quantized
                else jnp.asarray(logits, dtype=dtype)),
    )
