"""Model layer: functional transformer graphs for Llama 2/3/3.x and Qwen3."""

from .config import ModelConfig  # noqa: F401
from .llama import forward, init_random_params, load_params_from_mfile  # noqa: F401
