"""Model configuration — the runtime view of a .m header.

Carries everything the graph builder needs (reference: LlmHeader,
src/llm.hpp:42-71) plus TPU-side execution choices (compute dtype, weight
layout) that have no reference equivalent.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..formats.mfile import ArchType, HiddenAct, ModelHeader, RopeType


@dataclass(frozen=True)
class ModelConfig:
    arch: ArchType
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    vocab_size: int
    seq_len: int
    norm_epsilon: float
    rope_theta: float
    rope_type: RopeType
    rope_scaling_factor: float = 1.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    hidden_act: HiddenAct = HiddenAct.SILU
    n_experts: int = 0
    n_active_experts: int = 0
    # Renormalize the selected top-k router weights to sum to 1 (HF
    # norm_topk_prob; Mixtral semantics and Qwen3-MoE with norm_topk_prob
    # true). False keeps the raw softmax probabilities (sum < 1). Note:
    # softmax-then-topk-renorm and topk-then-softmax are the same function —
    # only the renorm-vs-raw choice changes behavior.
    moe_norm_topk: bool = True

    # TPU execution choices (no reference equivalent):
    compute_dtype: str = "float32"  # "float32" for parity, "bfloat16" for speed
    # attention implementation: "auto" = Pallas flash kernel on TPU when the
    # shapes fit (single-device graph), XLA oracle otherwise; "xla"/"flash"
    # force one. The TP/SP paths pick their own kernels inside shard_map.
    attn_impl: str = "auto"
    # Q80 activation-sync parity: reproduce the reference's Q80 cast points
    # in-graph (llm.cpp:258-265 casts; wire pipes SURVEY.md §2 #10) via
    # fake-quantization. Costs throughput; off for pure-TPU serving.
    sync_q80: bool = False
    # MoE compute: "sparse" = sort-by-expert + lax.ragged_dot grouped matmul
    # (O(k) experts per token); "dense" = all-experts einsum, gate-weighted
    # (O(E), exact and simple — the test oracle); "auto" = sparse.
    moe_impl: str = "auto"
    # Host-DRAM weight offload (70B/405B, BASELINE config 5): per-layer
    # weights live in pinned host memory and stream to device memory inside
    # the scan (layer ℓ+1's transfer overlaps layer ℓ's compute under XLA's
    # latency-hiding scheduler). Set via --weight-mode offload; the loader
    # places the layer stack host-side to match. No reference equivalent —
    # the reference keeps shards resident (SURVEY.md §7.4).
    offload: bool = False
    # Compute/communication overlap for the two per-layer tp partial merges
    # (wo and w2 — the reference's SYNC steps): > 0 splits each merge's
    # model-dim into this many chunks reduced by independent ppermute ring
    # chains (parallel/qcollectives.overlapped_wire_psum) so chunk i's hops
    # overlap chunk i+1's compute under XLA's latency-hiding scheduler
    # (TokenWeave shape, PAPERS.md). 0 keeps the monolithic GSPMD psum.
    # Resolved by the engine from --comm-overlap {off,auto,N}; static trace
    # config, so it is part of the multihost cluster fingerprint.
    comm_overlap: int = 0

    @property
    def q_dim(self) -> int:
        return self.head_dim * self.n_heads

    @property
    def kv_dim(self) -> int:
        return self.head_dim * self.n_kv_heads

    @property
    def kv_mul(self) -> int:
        """GQA group size (reference: multiheadAtt_F32 kvMul, nn-cpu-ops.cpp:756)."""
        return self.n_heads // self.n_kv_heads

    @property
    def uses_qk_norm(self) -> bool:
        """Qwen3 applies per-head RMS norm to q/k before rope (llm.cpp:285-309)."""
        return self.arch == ArchType.QWEN3

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @classmethod
    def from_header(cls, h: ModelHeader, compute_dtype: str = "float32") -> "ModelConfig":
        from ..formats.quants import Q80

        return cls(
            sync_q80=h.sync_type == Q80,
            arch=h.arch_type,
            dim=h.dim,
            hidden_dim=h.hidden_dim,
            n_layers=h.n_layers,
            n_heads=h.n_heads,
            n_kv_heads=h.n_kv_heads,
            head_dim=h.head_dim,
            vocab_size=h.vocab_size,
            seq_len=h.seq_len,
            norm_epsilon=h.norm_epsilon,
            rope_theta=h.rope_theta,
            rope_type=h.rope_type,
            rope_scaling_factor=h.rope_scaling_factor,
            rope_scaling_low_freq_factor=h.rope_scaling_low_freq_factor,
            rope_scaling_high_freq_factor=h.rope_scaling_high_freq_factor,
            rope_scaling_orig_max_seq_len=h.rope_scaling_orig_max_seq_len,
            hidden_act=h.hidden_act,
            n_experts=h.n_experts,
            n_active_experts=h.n_active_experts,
            moe_norm_topk=bool(h.moe_norm_topk),
            compute_dtype=compute_dtype,
        )

    def with_seq_len(self, seq_len: int) -> "ModelConfig":
        return replace(self, seq_len=seq_len)
