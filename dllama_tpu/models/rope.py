"""RoPE frequency caches and application.

Numerically matches the reference's precomputed-cache approach (reference:
fullfillRopeLlamaCache / fullfillRopeFalconCache, src/nn/nn-core.cpp:329-370;
apply kernels ropeLlama_F32 / ropeFalcon_F32, src/nn/nn-cpu-ops.cpp:836-878):

* **llama style** — adjacent interleaved pairs ``(x[2j], x[2j+1])`` within each
  head, frequency ``theta^(-2j/head_dim)``. Used by Llama 2/3 together with the
  converter's Q/K head permutation (convert-hf.py:12-15).
* **llama3.1** — llama pairing with Meta's wavelength-banded frequency scaling
  (scaleFrequencyLlama3, nn-core.cpp:313-327).
* **falcon (neox) style** — half-split pairs ``(x[j], x[j + head_dim/2])``,
  same frequencies. Used by Qwen3.

Unlike the reference, the cache here is global per model (``[seq_len,
head_dim/2]``), not per-TP-shard: the TP shard always holds whole heads, and
every head uses identical frequencies, so slicing the cache per node
(sliceRope, nn-core.cpp:232-263) is unnecessary under SPMD sharding.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..formats.mfile import RopeType
from .config import ModelConfig


def _scale_frequency_llama3(freq: np.ndarray, cfg: ModelConfig) -> np.ndarray:
    """Meta's llama3.1 rope scaling (reference: nn-core.cpp:313-327)."""
    wave_len = 2.0 * np.pi / freq
    high_freq_wavelen = cfg.rope_scaling_orig_max_seq_len / cfg.rope_scaling_high_freq_factor
    low_freq_wavelen = cfg.rope_scaling_orig_max_seq_len / cfg.rope_scaling_low_freq_factor
    smooth = (cfg.rope_scaling_orig_max_seq_len / wave_len - cfg.rope_scaling_low_freq_factor) / (
        cfg.rope_scaling_high_freq_factor - cfg.rope_scaling_low_freq_factor)
    smoothed = (1.0 - smooth) * freq / cfg.rope_scaling_factor + smooth * freq
    out = np.where(wave_len < high_freq_wavelen, freq,
                   np.where(wave_len > low_freq_wavelen,
                            freq / cfg.rope_scaling_factor, smoothed))
    return out


import functools


@functools.lru_cache(maxsize=16)
def build_rope_cache(cfg: ModelConfig) -> tuple[np.ndarray, np.ndarray]:
    """cos/sin caches of shape ``[seq_len, head_dim // 2]`` in float32.

    Memoized per config (frozen dataclass): the host-side trig tables are
    computed once per model, not per trace. Returns plain numpy arrays —
    callers may be inside a jit trace, where caching a ``jnp`` constant would
    leak a tracer; numpy constants embed safely."""
    half = cfg.head_dim // 2
    j = np.arange(half, dtype=np.float32)
    # llama: pair index j covers dims (2j, 2j+1), h = 2j in the reference loop.
    # falcon: freq exponent is 2j/head_dim as well (nn-core.cpp:354) — the two
    # styles share frequencies and differ only in pairing layout.
    freqs = 1.0 / np.power(cfg.rope_theta, 2.0 * j / cfg.head_dim, dtype=np.float32)
    if cfg.rope_type == RopeType.LLAMA3_1 and cfg.rope_scaling_factor != 1.0:
        freqs = _scale_frequency_llama3(freqs.astype(np.float64), cfg).astype(np.float32)
    pos = np.arange(cfg.seq_len, dtype=np.float32)[:, None]
    angles = pos * freqs[None, :]
    return np.cos(angles), np.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               positions: jnp.ndarray, rope_type: RopeType) -> jnp.ndarray:
    """Rotate ``x: [B, T, n_heads, head_dim]`` at ``positions: [B, T]``."""
    dtype = x.dtype
    c = jnp.asarray(cos)[positions]  # [B, T, half] float32
    s = jnp.asarray(sin)[positions]
    c = c[:, :, None, :]  # broadcast over heads
    s = s[:, :, None, :]
    xf = x.astype(jnp.float32)  # rotate in f32, cast back (parity + no promotion)
    if rope_type in (RopeType.LLAMA, RopeType.LLAMA3_1):
        x0 = xf[..., 0::2]
        x1 = xf[..., 1::2]
        r0 = x0 * c - x1 * s
        r1 = x0 * s + x1 * c
        # re-interleave: stack on a new trailing axis then flatten
        return jnp.stack([r0, r1], axis=-1).reshape(x.shape).astype(dtype)
    elif rope_type == RopeType.FALCON:
        half = x.shape[-1] // 2
        x0 = xf[..., :half]
        x1 = xf[..., half:]
        r0 = x0 * c - x1 * s
        r1 = x0 * s + x1 * c
        return jnp.concatenate([r0, r1], axis=-1).astype(dtype)
    raise ValueError(f"unsupported rope type {rope_type}")
