#!/bin/sh
# Local multi-process cluster — the reference's examples/n-workers.sh for the
# SPMD runtime: every process (root included) runs the same binary with the
# same model files; workers join via the jax.distributed coordinator.
#
# Usage: MODEL=m.m TOKENIZER=t.t NPROCS=4 sh examples/n-workers.sh "prompt"
set -e
MODEL=${MODEL:?set MODEL=path/to.m}
TOKENIZER=${TOKENIZER:?set TOKENIZER=path/to.t}
NPROCS=${NPROCS:-2}
COORD=${COORD:-127.0.0.1:19917}
PROMPT=${1:-"Hello world"}

i=1
while [ "$i" -lt "$NPROCS" ]; do
    python -m dllama_tpu worker \
        --coordinator "$COORD" --nprocs "$NPROCS" --procid "$i" \
        --model "$MODEL" --tokenizer "$TOKENIZER" --tp "$NPROCS" \
        --worker-reserve --worker-timeout 300 &
    i=$((i + 1))
done

python -m dllama_tpu inference \
    --coordinator "$COORD" --nprocs "$NPROCS" --procid 0 \
    --model "$MODEL" --tokenizer "$TOKENIZER" --tp "$NPROCS" \
    --prompt "$PROMPT" --steps 128 --temperature 0
wait
