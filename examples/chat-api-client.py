#!/usr/bin/env python
"""Minimal OpenAI-compatible chat client for the dllama-tpu API server
(the reference ships examples/chat-api-client.js; same endpoint shape).

Start a server first:
    python -m dllama_tpu api --model m.m --tokenizer t.t --port 9990
Then:
    python examples/chat-api-client.py "Hello there" --stream
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("prompt")
    ap.add_argument("--url", default="http://127.0.0.1:9990")
    ap.add_argument("--max-tokens", type=int, default=128)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--stop", action="append", default=None,
                    help="custom stop string (repeatable)")
    ap.add_argument("--stream", action="store_true")
    args = ap.parse_args()

    body = {
        "messages": [{"role": "user", "content": args.prompt}],
        "max_tokens": args.max_tokens,
        "temperature": args.temperature,
        "stream": args.stream,
    }
    if args.stop:
        body["stop"] = args.stop
    req = urllib.request.Request(
        args.url + "/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        if not args.stream:
            data = json.loads(r.read())
            choice = data["choices"][0]
            print(choice["message"]["content"])
            print(f"\n[{choice['finish_reason']}] usage: {data['usage']}",
                  file=sys.stderr)
            return
        for raw in r:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            delta = json.loads(payload)["choices"][0]["delta"]
            sys.stdout.write(delta.get("content", ""))
            sys.stdout.flush()
        print()


if __name__ == "__main__":
    main()
