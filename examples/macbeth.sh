#!/bin/sh
# Fixed-seed determinism check — the reference's examples/macbeth.sh without
# needing a real checkpoint: the committed reference-binary goldens play the
# same role (tests/goldens/llama_macbeth_f32.json is a 2049-token transcript
# from the actual reference binary), replayed by:
set -e
cd "$(dirname "$0")/.."
python -m pytest tests/test_golden_reference.py -q -k macbeth
