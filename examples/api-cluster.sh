#!/bin/sh
# OpenAI-compatible API server over a worker mesh — the reference's
# dllama-api deployment shape (src/dllama-api.cpp:599-613: the HTTP server
# runs on the root and drives the same worker mesh the CLI uses), with
# continuous batching riding the CTRL_SRV_* mirror protocol
# (runtime/serving.py + parallel/multihost.py).
#
# Usage: MODEL=m.m TOKENIZER=t.t NPROCS=2 sh examples/api-cluster.sh
# Then:  curl http://127.0.0.1:9990/v1/chat/completions -d '{
#          "model":"m","messages":[{"role":"user","content":"hi"}]}'
set -e
MODEL=${MODEL:?set MODEL=path/to.m}
TOKENIZER=${TOKENIZER:?set TOKENIZER=path/to.t}
NPROCS=${NPROCS:-2}
COORD=${COORD:-127.0.0.1:19917}
PORT=${PORT:-9990}
SLOTS=${SLOTS:-4}

i=1
while [ "$i" -lt "$NPROCS" ]; do
  # flags that select a jitted program (--compute-dtype here) must match the
  # root's exactly — the cluster fingerprint rejects mismatches at init. No
  # --worker-timeout: an idle API server sends no control packets, so any
  # bounded wait would kill the mesh between requests; root death still
  # surfaces as a coordination-service error and --worker-reserve re-serves.
  python -m dllama_tpu worker \
    --coordinator "$COORD" --nprocs "$NPROCS" --procid "$i" \
    --model "$MODEL" --tokenizer "$TOKENIZER" --tp "$NPROCS" \
    --compute-dtype bf16 --worker-reserve &
  i=$((i + 1))
done

exec python -m dllama_tpu api \
  --coordinator "$COORD" --nprocs "$NPROCS" --procid 0 \
  --model "$MODEL" --tokenizer "$TOKENIZER" --tp "$NPROCS" \
  --batch-slots "$SLOTS" --port "$PORT" --compute-dtype bf16
