#!/usr/bin/env python
"""Benchmark: single-chip decode throughput on a Llama-3.2-1B-shaped Q40 model.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` is the fraction of the north-star target rate (BASELINE.json:
>=1000 tok/s/chip for Llama-3.1-8B Q40 on v5e-8; the reference's own published
numbers are Raspberry-Pi-class and not comparable, BASELINE.md). The benched
model here is 1B-shaped on ONE chip, so this is a provisional proxy until the
8B multi-chip bench lands; value > 1.0 does not yet mean the north star is met.

The decode loop is the TPU-idiomatic fused step: forward + on-device greedy
sampling, token fed back without host round-trips, KV cache donated.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from dllama_tpu.formats.mfile import ArchType, RopeType
from dllama_tpu.models import ModelConfig, forward
from dllama_tpu.models.llama import greedy_step
from dllama_tpu.runtime import KVCache

# Llama 3.2 1B shapes (HF config), seq capped for bench
CFG = ModelConfig(
    arch=ArchType.LLAMA, dim=2048, hidden_dim=8192, n_layers=16,
    n_heads=32, n_kv_heads=8, head_dim=64, vocab_size=128256, seq_len=1024,
    norm_epsilon=1e-5, rope_theta=500000.0, rope_type=RopeType.LLAMA3_1,
    rope_scaling_factor=32.0, rope_scaling_low_freq_factor=1.0,
    rope_scaling_high_freq_factor=4.0, rope_scaling_orig_max_seq_len=8192,
    compute_dtype="bfloat16",
)

PREFILL_LEN = 128
DECODE_STEPS = 64
NORTH_STAR_TOK_S = 1000.0


def _fast_random_params(cfg: ModelConfig):
    """Random Q40-plane params generated directly (no float quantization pass)
    — keeps bench startup fast on a single host core."""
    import numpy as np

    from dllama_tpu.models.llama import LayerParams, Params
    from dllama_tpu.ops.linear import QuantizedWeight

    rng = np.random.default_rng(0)

    def qw(out, in_):
        # K-major planes (see ops.linear.QuantizedWeight)
        return QuantizedWeight(
            scales=jnp.asarray(
                rng.random((cfg.n_layers, in_ // 32, out), dtype=np.float32)
                * 0.01 + 0.001),
            codes=jnp.asarray(
                rng.integers(-8, 8, (cfg.n_layers, in_, out), dtype=np.int8)),
        )

    ones = lambda *s: jnp.asarray(np.ones(s, dtype=np.float32))
    layers = LayerParams(
        wq=qw(cfg.q_dim, cfg.dim), wk=qw(cfg.kv_dim, cfg.dim),
        wv=qw(cfg.kv_dim, cfg.dim), wo=qw(cfg.dim, cfg.q_dim),
        w1=qw(cfg.hidden_dim, cfg.dim), w2=qw(cfg.dim, cfg.hidden_dim),
        w3=qw(cfg.hidden_dim, cfg.dim),
        norm_att=ones(cfg.n_layers, cfg.dim), norm_ffn=ones(cfg.n_layers, cfg.dim),
        norm_q=None, norm_k=None,
    )
    lw = QuantizedWeight(
        scales=jnp.asarray(rng.random((cfg.dim // 32, cfg.vocab_size),
                                      dtype=np.float32) * 0.01),
        codes=jnp.asarray(rng.integers(-8, 8, (cfg.dim, cfg.vocab_size),
                                       dtype=np.int8)))
    emb = rng.random((cfg.vocab_size, cfg.dim), dtype=np.float32) * 0.02
    return Params(embedding=jnp.asarray(emb), layers=layers,
                  final_norm=ones(cfg.dim), logits=lw)


def main() -> None:
    params = jax.device_put(_fast_random_params(CFG))
    kv = KVCache.create(CFG, dtype=jnp.bfloat16)

    # the engine's greedy fast path: forward + argmax fused into ONE dispatch
    # per token — the exact production step (engine.next_token)
    step = jax.jit(forward, static_argnums=1, donate_argnums=(4,))
    greedy = jax.jit(greedy_step, static_argnums=1, donate_argnums=(4,))

    # prefill
    prompt = jnp.ones((1, PREFILL_LEN), dtype=jnp.int32)
    t0 = time.perf_counter()
    logits, kv = step(params, CFG, prompt, jnp.int32(0), kv)
    token = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
    token.block_until_ready()
    prefill_compile_s = time.perf_counter() - t0

    # decode warmup (compile T=1 path)
    token, kv = greedy(params, CFG, token[:, None], jnp.int32(PREFILL_LEN), kv)
    token.block_until_ready()

    t0 = time.perf_counter()
    pos = PREFILL_LEN + 1
    for i in range(DECODE_STEPS):
        token, kv = greedy(params, CFG, token[:, None], jnp.int32(pos + i), kv)
    token.block_until_ready()
    dt = time.perf_counter() - t0

    tok_s = DECODE_STEPS / dt
    print(json.dumps({
        "metric": "decode_tok_per_s_llama1b_q40_1chip",
        "value": round(tok_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / NORTH_STAR_TOK_S, 4),
    }))


if __name__ == "__main__":
    main()
