#!/usr/bin/env python
"""Benchmark: single-chip decode/prefill throughput on Llama-shaped Q40 models.

Prints exactly ONE JSON line:
    {"metric", "value", "unit", "vs_baseline", ...extras, "error"}

and always exits 0 with that line present, even when the TPU backend is down —
round 1 lost its whole capture window to a hanging backend init
(BENCH_r01.json rc=1), so this version:

1. probes backend init in a SUBPROCESS with a bounded wait (first jit/init on
   TPU is 20-40s; the probe allows 150s, retried up to 3x), and
2. wraps every stage in a deadline so a partial result still emits the line.

Headline metric: decode tok/s for the **Llama-3.1-8B shape** (the BASELINE
north-star model; Q40 planes ≈ 8.5 GB fit one 16 GB v5e chip). Physics
context for `vs_baseline`: the north star (>=1000 tok/s for 8B Q40) is an
8-chip v5e-8 aggregate-bandwidth target; a single chip's roofline is
~`hbm_GBps / weight_GB` ≈ 90-150 tok/s for this shape, so 1-chip values are
reported as-is and the roofline estimate ships in the extras for honest
comparison. Extras also carry prefill tok/s, prefill MFU, a batch-16 decode
aggregate (serving throughput; beyond the single-sequence reference), and a
secondary 1B-shape number (round-1 comparability).

The decode loop is the engine's production fast path: forward + on-device
argmax fused into one dispatch (models.llama.greedy_step), KV donated.

TIMING METHODOLOGY (round 4): on the axon tunnel ``jax.block_until_ready``
returns WITHOUT waiting for device execution (tools/hw_probe.py measured a
2 GiB reduction "completing" in 20 us and an 8B decode "faster" than 1B —
pure enqueue rates; the rounds-1-3 capture numbers were invalid for this
reason).  Every measured region therefore ends with ``jax.device_get`` of a
small value that data-depends on the computation — the runtime cannot
produce real bytes without executing the chain — and subtracts the
separately-measured host<->device round-trip (~67 ms on the tunnel) once
per region.  A region whose net time is smaller than the RTT itself is
reported as null (measurement floor) rather than as an inflated rate."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

NORTH_STAR_TOK_S = 1000.0  # BASELINE.json north star (8B Q40, v5e-8)
PROBE_TIMEOUT_S = float(os.environ.get("DLLAMA_BENCH_PROBE_TIMEOUT", "150"))
PROBE_RETRIES = int(os.environ.get("DLLAMA_BENCH_PROBE_RETRIES", "3"))
STAGE_DEADLINE_S = float(os.environ.get("DLLAMA_BENCH_STAGE_DEADLINE", "600"))

def _roofline_mod():
    """The roofline observatory's ceilings table + rate math
    (dllama_tpu/runtime/roofline.py), loaded BY FILE PATH: importing the
    package would pull jax (runtime/__init__ imports the KV cache), and
    the bench parent stays jax-free by design — a wedged PJRT import
    must not stall its emit path. The module's join functions import
    telemetry lazily, so the standalone load carries exactly the
    ceilings/rate surface the parent needs."""
    global _ROOFLINE_MOD
    try:
        return _ROOFLINE_MOD
    except NameError:
        pass
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "dllama_tpu", "runtime", "roofline.py")
    spec = importlib.util.spec_from_file_location("_dllama_roofline", p)
    mod = importlib.util.module_from_spec(spec)
    # register BEFORE exec: dataclasses resolves string annotations via
    # sys.modules[cls.__module__] at class-creation time
    sys.modules["_dllama_roofline"] = mod
    spec.loader.exec_module(mod)
    _ROOFLINE_MOD = mod
    return mod


def detect_specs(device_kind: str) -> tuple[float, float]:
    """Nameplate (tflops, gbps) by device kind — ONE table for the whole
    repo (roofline.NAMEPLATE_SPECS; this wrapper keeps the historical
    bench signature)."""
    c = _roofline_mod().nameplate_ceilings(device_kind)
    return c.tflops, c.hbm_gbps


def emit(result: dict) -> None:
    print(json.dumps(result))
    sys.stdout.flush()


def _tail(b) -> str:
    if not b:
        return ""
    if isinstance(b, bytes):
        b = b.decode(errors="replace")
    return b[-600:]


def force_platform_from_env() -> str | None:
    """Apply the DLLAMA_BENCH_PLATFORM override in-process (sitecustomize
    rewrites the bare JAX_PLATFORMS env var on every interpreter start, so
    only jax.config.update sticks). For jax-importing processes ONLY —
    stage children and the profiling tools; the bench PARENT stays jax-free
    by design (a wedged PJRT import must not stall its emit path) and keeps
    its env-var write."""
    force = os.environ.get("DLLAMA_BENCH_PLATFORM")
    if force:
        import jax

        jax.config.update("jax_platforms", force)
    return force


def probe_once(platform: str | None, attempts: list) -> str | None:
    """One backend-probe subprocess; returns the device-info JSON line on
    success, None on failure. Every attempt's forensics (rc, duration,
    partial stdout/stderr — including a timed-out child's captured output)
    land in ``attempts`` so BENCH_rN.json can pin an environment-side hang
    even when nothing succeeds (VERDICT round-2 next #1).

    The platform override is applied INSIDE the child (after interpreter
    startup): this image's sitecustomize rewrites JAX_PLATFORMS on every
    python start, so an inherited env var would be clobbered."""
    setenv = (
        f"import os; os.environ['JAX_PLATFORMS'] = {platform!r}; "
        f"import jax; jax.config.update('jax_platforms', {platform!r}); "
        if platform else "")
    code = (
        f"{setenv}import jax, json, sys; "
        "print('probe: importing done', file=sys.stderr, flush=True); "
        "d = jax.devices(); "
        "print(json.dumps({'platform': d[0].platform, "
        "'kind': d[0].device_kind, 'n': len(d)}))"
    )
    rec: dict = {"platform_arg": platform}
    t0 = time.monotonic()
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, timeout=PROBE_TIMEOUT_S)
        rec.update(rc=out.returncode, stdout=_tail(out.stdout),
                   stderr=_tail(out.stderr))
        lines = out.stdout.decode(errors="replace").strip().splitlines()
        if out.returncode == 0 and lines:
            rec["ok"] = True
            attempts.append(rec)
            return lines[-1]
    except subprocess.TimeoutExpired as e:
        # keep the timed-out child's partial output — the key forensic:
        # "importing done + silence" = backend init hang, not our code
        rec.update(timeout_s=PROBE_TIMEOUT_S, stdout=_tail(e.stdout),
                   stderr=_tail(e.stderr))
    rec["ok"] = False
    rec["duration_s"] = round(time.monotonic() - t0, 1)
    attempts.append(rec)
    return None


def probe_backend(platform: str | None, attempts: list) -> tuple[bool, str]:
    """Probe schedule: default platform x PROBE_RETRIES, then explicit
    'axon' and 'tpu' overrides (the live chip rides the axon plugin; if the
    default resolution wedges, an explicit pin may not). Returns (ok, detail):
    detail is the device-info JSON on success, else a summary string."""
    plans: list = [platform] * PROBE_RETRIES
    if platform is None:
        plans += ["axon", "tpu"]
    for p in plans:
        info = probe_once(p, attempts)
        if info is not None:
            return True, info
        time.sleep(5)
    fails = [a.get("stderr") or f"rc={a.get('rc')}" if "timeout_s" not in a
             else f"init exceeded {a['timeout_s']}s" for a in attempts]
    return False, f"{len(attempts)} probe attempts failed; last: {fails[-1]}"


# ---------------------------------------------------------------------------
# model shapes
# ---------------------------------------------------------------------------


# plain-int shape table: the parent process computes rooflines from these
# WITHOUT importing jax/dllama_tpu (a wedged PJRT plugin import would stall
# the parent's emit path — measurement is the children's job)
PRESETS = {
    "8b": dict(dim=4096, hidden_dim=14336, n_layers=32, n_heads=32,
               n_kv_heads=8, head_dim=128, vocab_size=128256, seq_len=1024),
    "1b": dict(dim=2048, hidden_dim=8192, n_layers=16, n_heads=32,
               n_kv_heads=8, head_dim=64, vocab_size=128256, seq_len=1024),
    "tiny": dict(dim=256, hidden_dim=512, n_layers=2, n_heads=4,
                 n_kv_heads=2, head_dim=64, vocab_size=2048, seq_len=256),
}


def model_cfg(preset: str):
    from dllama_tpu.formats.mfile import ArchType, RopeType
    from dllama_tpu.models import ModelConfig

    return ModelConfig(
        arch=ArchType.LLAMA, norm_epsilon=1e-5,
        rope_theta=500000.0, rope_type=RopeType.LLAMA3_1,
        rope_scaling_factor=32.0, rope_scaling_low_freq_factor=1.0,
        rope_scaling_high_freq_factor=4.0, rope_scaling_orig_max_seq_len=8192,
        compute_dtype="bfloat16",
        # tools/perf_matrix.py sweeps kernel choices through these knobs
        attn_impl=os.environ.get("DLLAMA_BENCH_ATTN", "auto"),
        **PRESETS[preset])


def matmul_param_count(preset: str) -> int:
    """Weights touched per token (matmul planes; the HBM-bandwidth payload)."""
    p = PRESETS[preset]
    q_dim = p["n_heads"] * p["head_dim"]
    kv_dim = p["n_kv_heads"] * p["head_dim"]
    per_layer = (p["dim"] * q_dim + 2 * p["dim"] * kv_dim
                 + q_dim * p["dim"] + 3 * p["dim"] * p["hidden_dim"])
    return p["n_layers"] * per_layer + p["dim"] * p["vocab_size"]


def _codes_kernel():
    """Process-wide jitted Q40-code RNG (lazy: jax imports only on use).
    A per-call closure would recompile every code shape for each of the
    three bench_preset invocations — jit caches key on function identity."""
    global _CODES_JIT
    try:
        return _CODES_JIT
    except NameError:
        pass
    from functools import partial

    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnums=1)
    def _codes(k, shape):
        bits = jax.random.bits(k, shape, jnp.uint8)  # 1 B/elem of entropy
        return (bits & jnp.uint8(0x0F)).astype(jnp.int8) - 8  # [-8, 8)

    _CODES_JIT = _codes
    return _codes


def bench_weight_repr() -> str:
    """On-device weight representation for the bench stages: ``q40``
    (default — the production quantized planes) or ``bf16``
    (DLLAMA_BENCH_WEIGHTS=bf16: dense planes, the engine's
    ``--weight-mode bf16``). The dense row measures the NO-DEQUANT
    streaming ceiling — on the 1b preset it fits HBM and isolates how
    much of the decode gap is the fused dequant's VPU work."""
    w = os.environ.get("DLLAMA_BENCH_WEIGHTS", "q40")
    if w not in ("q40", "bf16"):
        raise ValueError(f"DLLAMA_BENCH_WEIGHTS must be q40|bf16, got {w!r}")
    return w


def device_random_params(cfg):
    """Random Q40-plane params generated ON DEVICE (no host RAM spike, no
    multi-GB host->device transfer: an 8B-shape Q40 stack is ~8.5 GB).

    Each tensor is built inside one jit so XLA fuses the RNG + mask + cast
    chain into the output buffer. The eager version OOM-wedged the chip:
    `randint` drew uint32 bits — a 7.5 GB intermediate for the stacked
    (32, 14336, 4096) ffn codes alone, on a 16 GB chip that already held
    earlier planes (the round-1/2 'backend hang' during the 8B stage)."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.models.llama import LayerParams, Params
    from dllama_tpu.ops.linear import QuantizedWeight, fast_numerics_resolved
    from dllama_tpu.runtime.weights import dense_logits_resolved

    key = iter(jax.random.split(jax.random.PRNGKey(0), 32))
    _codes = _codes_kernel()
    # mirror the production load config (runtime.weights._StreamingLoader):
    # fast numerics store bf16 scales and a resident dense-bf16 logits head
    fast = fast_numerics_resolved(cfg.compute_dtype)
    scale_dtype = jnp.bfloat16 if fast else jnp.float32

    dense_w = bench_weight_repr() == "bf16"

    def qw(out, in_, stacked=True):
        if dense_w:
            # dense planes use the reference [out, in] orientation
            shape_d = (cfg.n_layers, out, in_) if stacked else (out, in_)
            return jax.random.uniform(next(key), shape_d, jnp.bfloat16,
                                      minval=-0.02, maxval=0.02)
        shape_s = (cfg.n_layers, in_ // 32, out) if stacked else (in_ // 32, out)
        shape_c = (cfg.n_layers, in_, out) if stacked else (in_, out)
        scales = jax.random.uniform(next(key), shape_s, scale_dtype,
                                    minval=0.001, maxval=0.011)
        codes = jax.block_until_ready(_codes(next(key), shape_c))
        return QuantizedWeight(scales=scales, codes=codes)

    ones = lambda *s: jnp.ones(s, dtype=jnp.float32)
    layers = LayerParams(
        wq=qw(cfg.q_dim, cfg.dim), wk=qw(cfg.kv_dim, cfg.dim),
        wv=qw(cfg.kv_dim, cfg.dim), wo=qw(cfg.dim, cfg.q_dim),
        w1=qw(cfg.hidden_dim, cfg.dim), w2=qw(cfg.dim, cfg.hidden_dim),
        w3=qw(cfg.hidden_dim, cfg.dim),
        norm_att=ones(cfg.n_layers, cfg.dim), norm_ffn=ones(cfg.n_layers, cfg.dim),
        norm_q=None, norm_k=None,
    )
    emb = (jax.random.uniform(next(key), (cfg.vocab_size, cfg.dim),
                              jnp.bfloat16, minval=-0.02, maxval=0.02))
    if dense_logits_resolved(cfg.compute_dtype):
        # dense head in the reference's [out, in] orientation (ops.linear)
        logits = jax.random.uniform(next(key), (cfg.vocab_size, cfg.dim),
                                    jnp.bfloat16, minval=-0.02, maxval=0.02)
    else:
        logits = qw(cfg.vocab_size, cfg.dim, stacked=False)
    return Params(embedding=emb, layers=layers, final_norm=ones(cfg.dim),
                  logits=logits)


# ---------------------------------------------------------------------------
# measured stages
# ---------------------------------------------------------------------------


class _PhaseDict(dict):
    """Stage-result dict that streams each phase transition to stdout as a
    JSON line, so the parent process can pin a wedge to its exact phase even
    when the child never returns."""

    def __setitem__(self, k, v):
        super().__setitem__(k, v)
        if k == "phase":
            print(json.dumps({"phase": v}), flush=True)


def stage_child(spec: str) -> None:
    """``bench.py --stage <spec>`` child entry: run ONE measurement stage in
    this process and print ``{"stage_result": ...}``. Isolation is the point:
    a chip wedge (the round-1/2 failure) kills this child, not the bench —
    the parent kills us at its per-stage budget and moves on.

    spec: preset name, optionally ``@b16`` (batched-serving variant) or
    ``@s8k`` (8192-token context: long-context decode is KV-bandwidth-bound,
    which is what ``--kv-dtype f8`` halves)."""
    force_platform_from_env()
    preset, _, mod = spec.partition("@")
    budget = float(os.environ.get("DLLAMA_BENCH_CHILD_BUDGET", STAGE_DEADLINE_S))
    deadline = time.monotonic() + budget
    kwargs = (dict(decode_steps=32, prefill_len=128, batch=16)
              if mod == "b16" else
              dict(seq_len=8192) if mod == "s8k" else {})
    st = _PhaseDict()
    try:
        if preset in SCENARIOS:
            SCENARIO_FNS[preset](deadline, out=st)
        else:
            bench_preset(preset, deadline, out=st, **kwargs)
    except Exception as e:  # noqa: BLE001 — the parent needs the line
        st["error"] = f"{type(e).__name__}: {e}"[:300]
    print(json.dumps({"stage_result": dict(st)}), flush=True)


CHIP_LOCK = "/tmp/dllama-chip.lock"
# stage children currently holding chip residency (the watchdog must kill
# them before force-exiting — a force-exit releases the chip lock while an
# orphan keeps the model staged: the double-residency the lock prevents)
_LIVE_CHILDREN: set = set()
# seconds spent WAITING for the chip lock this run: legitimate contention,
# not a wedge — main's watchdog extends its deadline by this
_LOCK_WAIT_TOTAL = [0.0]


class _chip_lock:
    """Exclusive cross-process lock around anything that stages a model on
    the chip. Two concurrent 8B residencies (the driver's end-of-round bench
    interleaving with the watcher's capture in the same healthy window)
    would OOM-wedge the backend for hours — the round-1/2/4 failure mode.
    Per-STAGE granularity so both holders make progress; falls through
    after ``timeout`` (measuring under contention beats not measuring)."""

    def __init__(self, timeout: float = 900.0):
        self._timeout = timeout
        self._fh = None

    def __enter__(self):
        import fcntl

        try:
            self._fh = open(CHIP_LOCK, "a+")
        except OSError as e:
            print(f"chip lock unavailable ({e}); proceeding UNLOCKED",
                  file=sys.stderr, flush=True)
            return self
        t0 = time.monotonic()
        while True:
            try:
                fcntl.flock(self._fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
                return self
            except OSError:
                if time.monotonic() - t0 > self._timeout:
                    print(f"chip lock not acquired in {self._timeout:.0f}s; "
                          f"proceeding UNLOCKED (contention beats silence)",
                          file=sys.stderr, flush=True)
                    return self
                time.sleep(2.0)

    def __exit__(self, *exc):
        if self._fh is not None:
            import fcntl

            try:
                fcntl.flock(self._fh, fcntl.LOCK_UN)
            except OSError:
                pass
            self._fh.close()
        return False


def run_stage(spec: str, budget: float) -> dict:
    """Run one stage in a subprocess with a hard kill at ``budget``
    (holding the chip lock: see _chip_lock)."""
    import threading
    from collections import deque

    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ,
               DLLAMA_BENCH_CHILD_BUDGET=str(max(30.0, budget - 20.0)))
    rec: dict = {"phase": "spawn"}
    err_tail: deque = deque(maxlen=30)
    child = None
    threads: list = []
    t_lock = time.monotonic()
    with _chip_lock():
        # lock WAITING must not be charged to the wedge watchdog — the
        # accumulated wait extends the parent deadline (see main's watchdog)
        wait_s = time.monotonic() - t_lock
        _LOCK_WAIT_TOTAL[0] += wait_s
        if wait_s > 1.0:
            rec["lock_wait_s"] = round(wait_s, 1)
        child = subprocess.Popen(
            [sys.executable, os.path.join(here, "bench.py"), "--stage", spec],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=here)
        _LIVE_CHILDREN.add(child)

        def read_out():
            for line in child.stdout:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if "stage_result" in obj:
                    rec["result"] = obj["stage_result"]
                elif "phase" in obj:
                    rec["phase"] = obj["phase"]

        def read_err():  # drain: a full pipe would block the child
            for line in child.stderr:
                err_tail.append(line.rstrip())

        threads = [threading.Thread(target=read_out, daemon=True),
                   threading.Thread(target=read_err, daemon=True)]
        for th in threads:
            th.start()
        try:
            child.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            child.kill()
            rec["killed"] = f"stage killed at {budget:.0f}s budget"
            try:
                child.wait(timeout=10)  # reap; readers see EOF
            except subprocess.TimeoutExpired:
                pass
        finally:
            _LIVE_CHILDREN.discard(child)
    for th in threads:
        th.join(timeout=10)
    if "result" in rec:
        if "lock_wait_s" in rec and isinstance(rec["result"], dict):
            rec["result"]["lock_wait_s"] = rec["lock_wait_s"]
        return rec["result"]
    out = {"phase": rec.get("phase"),
           "error": rec.get("killed")
           or f"child rc={child.returncode} without a result"}
    if err_tail:
        out["stderr_tail"] = _tail("\n".join(list(err_tail)[-8:]))
    return out


def _make_sync():
    """Fetch-forced synchronization + the tunnel's RTT floor.

    Returns ``(sync, rtt_s)``: ``sync(x)`` device_gets one element that
    data-depends on ``x`` (forcing every enqueued producer to actually run —
    see module docstring), and ``rtt_s`` is the median round-trip of such a
    fetch on an already-materialized buffer, to subtract once per timed
    region."""
    import jax
    import jax.numpy as jnp

    def sync(x):
        leaf = jax.tree_util.tree_leaves(x)[0]
        jax.device_get(jnp.ravel(leaf)[0])

    probe = jax.jit(lambda x: x + 1)(jnp.zeros((8,), jnp.int32))
    sync(probe)  # compile the ravel/index path
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        sync(probe)
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return sync, samples[2]


def _net(dt: float, rtt: float) -> float | None:
    """RTT-corrected region time, or None when the signal is smaller than
    the correction (a rate computed from it would be noise, not measurement
    — the round-1-3 failure mode this rework exists to kill)."""
    n = dt - rtt
    return n if n > rtt else None


# KV rows the post-prefill stages write (throwaways + decode + sampled +
# chunked + verify); prefill's position cycling stays below seq_len minus
# this so no stage writes past the cache. Stages that would still overrun
# (short-seq presets) are skipped with a row-budget check instead of
# silently clamping their writes onto stale tail rows.
_DECODE_REGION = 352


def bench_preset(preset: str, deadline: float, *, decode_steps: int = 64,
                 prefill_len: int = 256, batch: int = 1,
                 seq_len: int | None = None,
                 out: dict | None = None) -> dict:
    """Measure decode tok/s (+ prefill tok/s for batch=1) for one preset.

    ``out`` (when given) is filled INCREMENTALLY — including a ``phase``
    breadcrumb before every potentially-blocking jax call — so the watchdog's
    force-emitted JSON shows exactly where a wedged backend stopped
    (round-2's empty ``stages`` left that unanswerable)."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.models import forward
    from dllama_tpu.models.llama import greedy_step
    from dllama_tpu.runtime import KVCache

    out = {} if out is None else out
    out["phase"] = "budget_check"
    cfg = model_cfg(preset)
    if seq_len:
        from dataclasses import replace as _replace

        cfg = _replace(cfg, seq_len=seq_len)
    # record the quant numerics the stage ran so captures are attributable
    from dllama_tpu.ops.linear import quant_mode_label, turbo_mode

    out["quant_mode"] = quant_mode_label(cfg.compute_dtype == "bfloat16")
    out["weights"] = bench_weight_repr()
    if out["weights"] == "bf16" and turbo_mode() is not None:
        raise ValueError(
            "DLLAMA_BENCH_WEIGHTS=bf16 has no quantized planes to "
            "requantize — dense numerics would be mislabeled as turbo. "
            "If the turbo mode came from bench_promoted.json (the parent "
            "applies promotions), set DLLAMA_BENCH_NO_PROMO=1 for the "
            "dense-ceiling run")
    # pre-staging HBM guardrail (runtime.hbm): a preset that can't fit must
    # refuse HERE with a clean stage error — an OOM mid-staging wedges the
    # chip for hours (the round-1/2 outage; reference prints its own
    # required-memory estimate up front, nn-core.cpp:162-176)
    from dllama_tpu.runtime.hbm import check_budget, estimate_device_bytes

    _kv_map = {"bf16": jnp.bfloat16, "f8": jnp.float8_e4m3fn,
               "f32": jnp.float32}  # mirrors --kv-dtype (runtime/engine.py)
    kv_env = os.environ.get("DLLAMA_BENCH_KV", "bf16")
    if kv_env not in _kv_map:
        raise ValueError(
            f"DLLAMA_BENCH_KV must be one of {sorted(_kv_map)}, got {kv_env!r}")
    est = estimate_device_bytes(cfg, weight_repr=bench_weight_repr(),
                                kv_dtype_bytes=jnp.dtype(_kv_map[kv_env]).itemsize,
                                batch=batch)
    out["hbm_need_gb"] = round(est["need_per_device"] / 1024 ** 3, 2)
    limit = check_budget(est["need_per_device"], f"bench preset {preset}")
    if limit is not None:
        out["hbm_limit_gb"] = round(limit / 1024 ** 3, 2)

    out["phase"] = "params"
    sync, rtt = _make_sync()
    out["fetch_rtt_ms"] = round(1e3 * rtt, 1)
    params = device_random_params(cfg)
    jax.block_until_ready(params)  # staging is forced by the compile sync below
    if turbo_mode() is not None:
        # measure what the engine would serve: integer-dot planes (source
        # buffers freed leaf-by-leaf, same as the engine)
        from dllama_tpu.ops.turbo import turbo_params

        out["phase"] = "turbo_derive"
        params = turbo_params(params, a8=turbo_mode() == "a8")
        sync(params.layers.wq.w8)
    kv = KVCache.create(cfg, batch_size=batch, dtype=_kv_map[kv_env])

    step = jax.jit(forward, static_argnums=1, donate_argnums=(4,))
    greedy = jax.jit(greedy_step, static_argnums=1, donate_argnums=(4,))

    # prefill (chunked the way engine.prefill batches positions — the
    # production default's LARGEST bucket; the reference's fixed 32 would
    # idle the MXU)
    from dllama_tpu.runtime.engine import PREFILL_BUCKETS

    out["phase"] = "prefill_compile"
    # seq_len/4 cap keeps room for advancing measured chunks AND a decode
    # region after them on small presets (tiny: 256-seq -> 64-chunk)
    chunk = min(prefill_len, PREFILL_BUCKETS[0], cfg.seq_len // 4)
    prompt = jnp.ones((batch, chunk), dtype=jnp.int32)
    logits, kv = step(params, cfg, prompt, jnp.int32(0), kv)  # compile
    sync(logits)  # also warms the sync path for this shape
    if time.monotonic() > deadline:
        raise TimeoutError("deadline after prefill compile")
    # Measured dispatches advance positions like a real prefill (pos-0
    # repeats would let the flash kernel's causal block-skip drop the
    # attention over earlier chunks, inflating tok/s for multi-chunk
    # prompts). Enough dispatches ride one fetch to clear the RTT floor,
    # cycling through the positions the cache has; rows past
    # chunk*(cyc+1) stay free for the decode stages below.
    avail = cfg.seq_len // chunk - 1
    cyc = max(1, min(avail - 1, (cfg.seq_len - _DECODE_REGION) // chunk - 1))
    n_meas = 32
    out["phase"] = "prefill_measure"
    # one throwaway dispatch: the first dispatch after a compile absorbs
    # ~2 s of backlog on the tunnel even after a forced fetch (hw_probe)
    logits, kv = step(params, cfg, prompt, jnp.int32(chunk), kv)
    sync(logits)
    t0 = time.perf_counter()
    done = 0
    for i in range(n_meas):
        logits, kv = step(params, cfg, prompt,
                          jnp.int32(chunk * (1 + i % cyc)), kv)
        done += 1
        # enqueueing is cheap on TPU but each dispatch EXECUTES on the CPU
        # backend (bench self-test): respect the deadline mid-loop
        if done % 8 == 0 and time.monotonic() > deadline:
            break
    sync(logits)
    dt = _net(time.perf_counter() - t0, rtt)
    out["prefill_tok_per_s"] = round(batch * done * chunk / dt, 2) if dt else None
    pos = chunk * (cyc + 1)
    if done < n_meas:
        # deadline fired mid-prefill: stop HERE so the banked prefill number
        # reaches the parent (falling through to decode compile could eat
        # the child's kill headroom and lose the whole stage result)
        raise TimeoutError("deadline inside prefill measure")

    # decode (fused greedy step; token never leaves the device)
    out["phase"] = "decode_compile"
    token = jnp.ones((batch,), dtype=jnp.int32)
    token, kv = greedy(params, cfg, token[:, None], jnp.int32(pos), kv)  # compile
    sync(token)
    if time.monotonic() > deadline:
        raise TimeoutError("deadline after decode compile")
    out["phase"] = "decode_measure"
    pos += 1
    token, kv = greedy(params, cfg, token[:, None], jnp.int32(pos), kv)
    sync(token)  # throwaway: first-dispatch backlog (see prefill note)
    pos += 1
    t0 = time.perf_counter()
    for i in range(decode_steps):
        token, kv = greedy(params, cfg, token[:, None], jnp.int32(pos + i), kv)
    sync(token)
    dt = _net(time.perf_counter() - t0, rtt)
    out["decode_tok_per_s"] = round(batch * decode_steps / dt, 2) if dt else None
    out["decode_ms_per_step"] = round(1000.0 * dt / decode_steps, 3) if dt else None
    pos += decode_steps  # rows the loop above wrote

    # fused sampled decode (temperature/top-p on device, ops.sampling): the
    # serving path at temperature>0 — same dispatch budget as greedy
    if (batch == 1 and time.monotonic() < deadline
            and pos + 2 + max(8, decode_steps // 2) <= cfg.seq_len):
        from dllama_tpu.models.llama import sampled_step

        out["phase"] = "sampled_decode"
        sampled = jax.jit(sampled_step, static_argnums=1, donate_argnums=(4,))
        n = max(8, decode_steps // 2)
        token, kv = sampled(params, cfg, token[:, None], jnp.int32(pos), kv,
                            jnp.float32(0.8), jnp.float32(0.9), jnp.float32(0.5))
        sync(token)
        if time.monotonic() > deadline:
            return out  # keep the measured prefill/decode numbers
        pos += 1
        token, kv = sampled(params, cfg, token[:, None], jnp.int32(pos), kv,
                            jnp.float32(0.8), jnp.float32(0.9), jnp.float32(0.5))
        sync(token)  # throwaway
        pos += 1
        t0 = time.perf_counter()
        for i in range(n):
            token, kv = sampled(params, cfg, token[:, None],
                                jnp.int32(pos + i), kv, jnp.float32(0.8),
                                jnp.float32(0.9), jnp.float32(0.5))
        sync(token)
        dt = _net(time.perf_counter() - t0, rtt)
        out["sampled_decode_tok_per_s"] = round(n / dt, 2) if dt else None
        pos += n  # loop wrote rows [pos, pos + n); next free slot is pos + n

    # multi-step fused decode (decode_chunk): K tokens per dispatch — the
    # dispatch-overhead-free decode rate (engine --decode-chunk)
    if (batch == 1 and time.monotonic() < deadline
            and pos + 32 * (2 + max(1, decode_steps // 32)) <= cfg.seq_len):
        from dllama_tpu.models.llama import greedy_steps

        out["phase"] = "chunked_decode"
        gsteps = jax.jit(greedy_steps, static_argnums=(1, 5),
                         donate_argnums=(4,))
        K = 32
        toks, kv = gsteps(params, cfg, token, jnp.int32(pos), kv, K)  # compile
        sync(toks)
        if time.monotonic() > deadline:
            return out
        pos += K
        toks, kv = gsteps(params, cfg, toks[:, -1], jnp.int32(pos), kv, K)
        sync(toks)  # throwaway
        pos += K
        rounds = max(1, decode_steps // K)
        t0 = time.perf_counter()
        for r in range(rounds):
            toks, kv = gsteps(params, cfg, toks[:, -1], jnp.int32(pos + r * K),
                              kv, K)
        sync(toks)
        dt = _net(time.perf_counter() - t0, rtt)
        out["chunked_decode_tok_per_s"] = round(rounds * K / dt, 2) if dt else None

    # speculative verify cost: ms for a K=4 verify dispatch vs a plain decode
    # step. On an HBM-bound chip the ratio should approach 1.0 — that ratio
    # times the workload's acceptance rate is the --spec-lookup speedup.
    if (batch == 1 and time.monotonic() < deadline
            and pos + 5 * 19 <= cfg.seq_len):
        from dllama_tpu.models.llama import verify_step

        out["phase"] = "spec_verify"
        ver = jax.jit(verify_step, static_argnums=1, donate_argnums=(4,))
        vt = jnp.ones((1, 5), jnp.int32)
        _, preds0, kv = ver(params, cfg, vt, jnp.int32(pos), kv)  # compile
        sync(preds0)
        _, preds0, kv = ver(params, cfg, vt, jnp.int32(pos + 5), kv)
        sync(preds0)  # throwaway
        pos += 5
        if time.monotonic() < deadline:
            n = 16
            t0 = time.perf_counter()
            for i in range(n):
                n_acc, preds, kv = ver(params, cfg, vt,
                                       jnp.int32(pos + 5 * (i + 1)), kv)
            sync(preds)
            dt = _net(time.perf_counter() - t0, rtt)
            out["verify_k4_ms"] = round(1000.0 * dt / n, 3) if dt else None
            if out["verify_k4_ms"] and out.get("decode_ms_per_step"):
                out["verify_k4_over_decode"] = round(
                    out["verify_k4_ms"] / out["decode_ms_per_step"], 3)

    # paged decode (block-table KV, runtime/kvblocks.py): the continuous-
    # batching serving step measured on the SAME weights — one fused
    # dispatch through a block table, so the paged gather/kernel cost
    # becomes a ranked rate (and a roofline family below) instead of
    # staying invisible behind the --scenario path
    if batch == 1 and time.monotonic() < deadline:
        from dllama_tpu.models.llama import paged_forward
        from dllama_tpu.runtime.hbm import estimate_block_pool_bytes
        from dllama_tpu.runtime.kvblocks import PagedKVCache, blocks_per_seq

        out["phase"] = "paged_decode"
        bs_kv = 128
        m_blocks = blocks_per_seq(cfg.seq_len, bs_kv)
        kv_bytes = jnp.dtype(_kv_map[kv_env]).itemsize
        pool_bytes = estimate_block_pool_bytes(cfg, m_blocks + 1, bs_kv,
                                               kv_bytes)
        # the up-front guardrail priced weights + the DENSE cache only;
        # this stage's pool is extra residency, so it gets its own check
        # (conservative: the dense cache is deleted below but the probe
        # prices both) and a clean skip — never a mid-run OOM wedge
        try:
            check_budget(est["need_per_device"] + pool_bytes,
                         f"bench paged stage {preset}")
        except RuntimeError as e:
            out["paged_decode_skipped"] = str(e)[:200]
            out["phase"] = "done"
            return out
        del kv  # the dense pool: the paged stage holds its own
        pkv = PagedKVCache.create(cfg, n_blocks=m_blocks + 1,
                                  block_size=bs_kv, dtype=_kv_map[kv_env])
        tables = jnp.arange(1, m_blocks + 1, dtype=jnp.int32)[None, :]

        def paged_greedy(params, cfg, tokens, pos_vec, pkv, tables):
            logits, pkv = paged_forward(params, cfg, tokens, pos_vec, pkv,
                                        tables)
            return jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32), pkv

        pstep = jax.jit(paged_greedy, static_argnums=1, donate_argnums=(4,))
        ptok = jnp.ones((1,), jnp.int32)
        ptok, pkv = pstep(params, cfg, ptok[:, None],
                          jnp.zeros((1,), jnp.int32), pkv, tables)  # compile
        sync(ptok)
        if time.monotonic() < deadline:
            ptok, pkv = pstep(params, cfg, ptok[:, None],
                              jnp.ones((1,), jnp.int32), pkv, tables)
            sync(ptok)  # throwaway: first-dispatch backlog (see prefill note)
            n = max(8, decode_steps // 2)
            t0 = time.perf_counter()
            for i in range(n):
                ptok, pkv = pstep(params, cfg, ptok[:, None],
                                  jnp.full((1,), 2 + i, jnp.int32), pkv,
                                  tables)
            sync(ptok)
            dt = _net(time.perf_counter() - t0, rtt)
            out["paged_decode_tok_per_s"] = round(n / dt, 2) if dt else None
    out["phase"] = "done"
    return out


def _scn_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _pctl(sorted_vals: list, q: float):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def bench_continuous(deadline: float, *, out: dict | None = None) -> dict:
    """``--scenario continuous``: a mixed short/long staggered-arrival
    request stream through the paged continuous-batching scheduler
    (``--kv-block-size``, runtime/serving.PagedGenerator). The dense
    ``@b16`` stage measures raw batched dispatch rate on a full batch;
    this scenario measures what serving actually delivers under churn —
    sequences admit and retire mid-batch, chunked prefill interleaves
    with decode, and a third of the prompts share a 2-block prefix so
    block-level sharing is exercised. Reported fields (the ones
    tools/bench_compare.py diffs): aggregate ``agg_tok_per_s`` over the
    whole stream, TTFT percentiles (queue wait included — that IS the
    continuous-batching win), and block-pool occupancy/sharing peaks.

    The same wave then re-runs with speculative decoding on
    (``--spec-lookup``, runtime/serving.PagedGenerator's paged verify
    path) for a spec on/off A/B: ``accepted_tok_per_s`` (the spec-on
    wave's aggregate emitted tok/s — what acceptance actually bought),
    ``spec_accept_rate`` (accepted / drafted over the wave), and
    ``itl_p50_ms_delta`` (spec-on minus spec-off inter-token p50 —
    negative when speculation wins). tools/bench_compare.py ranks
    ``accepted_tok_per_s``; tools/perf_baseline.py guards it.

    A third wave exercises tiered KV memory (``--kv-host-blocks``): an
    idle/resume session stream over a device pool deliberately smaller
    than the sessions' combined KV, reporting ``sessions_per_chip``
    (idle sessions whose KV survived to resume via host spill +
    page-back) and ``resume_ttft_p95_ms`` — both ranked by
    tools/bench_compare.py and guarded by tools/perf_baseline.py
    (no_evidence until the next on-chip ``--baseline update``).

    Workload knobs (env): DLLAMA_BENCH_SCN_REQUESTS (24),
    DLLAMA_BENCH_SCN_SLOTS (4), DLLAMA_BENCH_KV_BLOCK (16),
    DLLAMA_BENCH_SCN_STAGGER (0.05 s), DLLAMA_BENCH_SCN_MAXTOK (16),
    DLLAMA_BENCH_SCN_SPEC (4 — the A/B's spec-lookup width),
    DLLAMA_BENCH_SCN_SESSIONS (10 — the tiered wave's session
    count)."""
    import shutil
    import tempfile
    import threading

    out = {} if out is None else out
    out["phase"] = "scenario_setup"
    here = os.path.dirname(os.path.abspath(__file__))
    # the scenario drives the REAL engine/scheduler stack, so it needs a
    # real .m/.t pair: synthesize the same tiny fixture the test tier uses
    sys.path.insert(0, os.path.join(here, "tests"))
    import numpy as np

    from helpers import (byte_vocab_tokenizer, tiny_header_params,
                         write_tiny_model)

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime import telemetry as tm
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.runtime.serving import BatchScheduler

    n_reqs = _scn_int("DLLAMA_BENCH_SCN_REQUESTS", 24)
    n_slots = _scn_int("DLLAMA_BENCH_SCN_SLOTS", 4)
    block = _scn_int("DLLAMA_BENCH_KV_BLOCK", 16)
    max_tok = _scn_int("DLLAMA_BENCH_SCN_MAXTOK", 16)
    stagger_s = float(os.environ.get("DLLAMA_BENCH_SCN_STAGGER", "0.05"))
    out.update(n_requests=n_reqs, n_slots=n_slots, kv_block_size=block)

    d = tempfile.mkdtemp(prefix="dllama-bench-scn-")
    try:
        mpath, tpath = os.path.join(d, "m.m"), os.path.join(d, "t.t")
        rng = np.random.default_rng(0xC0)
        write_tiny_model(mpath, tiny_header_params(
            dim=256, hidden_dim=512, n_layers=2, n_heads=4, n_kv_heads=2,
            head_dim=64, vocab_size=268, seq_len=256), rng)
        tfile.write_tfile(tpath, byte_vocab_tokenizer())

        # mixed workload: 1/3 long shared-prefix (RAG/system-prompt shape,
        # exercises block sharing + CoW), 1/3 short interactive, 1/3 long
        # distinct — arrivals staggered so admissions land mid-batch
        shared = [int(x) for x in rng.integers(1, 200, 2 * block)]
        prompts = []
        for i in range(n_reqs):
            if i % 3 == 0:
                prompts.append(shared
                               + [int(x) for x in rng.integers(1, 200, 48)])
            elif i % 3 == 1:
                prompts.append([int(x) for x in rng.integers(1, 200, 8)])
            else:
                prompts.append([int(x) for x in rng.integers(1, 200, 96)])

        out["phase"] = "scenario_engine"

        def wave(spec_k: int) -> dict:
            """One full staggered request wave through a fresh
            engine/scheduler at ``--spec-lookup=spec_k`` — the spec
            on/off A/B runs the IDENTICAL workload twice, so the two
            sides differ only in the verify path."""
            w: dict = {}
            eng = InferenceEngine(mpath, tpath, tp=1, kv_block_size=block,
                                  spec_lookup=spec_k)
            sched = BatchScheduler(eng, n_slots=n_slots)
            reg = tm.registry()
            g_total = reg.gauge(tm.KV_BLOCKS_TOTAL)
            g_used = reg.gauge(tm.KV_BLOCKS_USED)
            g_shared = reg.gauge(tm.KV_BLOCKS_SHARED)
            reuse = reg.counter(tm.PREFIX_REUSE_TOKENS)
            r0 = reuse.total()
            d0 = reg.counter(tm.SPEC_DRAFT_TOKENS).total()
            a0 = reg.counter(tm.SPEC_ACCEPTED_TOKENS).total()

            occ: list = []
            peaks = {"shared": 0.0}
            stop_sampling = threading.Event()

            def sample():
                while not stop_sampling.wait(0.05):
                    total = g_total.value() or 1
                    occ.append(g_used.value() / total)
                    peaks["shared"] = max(peaks["shared"],
                                          g_shared.value())

            sampler = threading.Thread(target=sample, daemon=True)
            sampler.start()

            t_sub: dict = {}
            t_toks: dict = {}  # per-request token stamps → real ITLs

            def mk_cb(i):
                def cb(tok, piece):
                    t_toks.setdefault(i, []).append(time.perf_counter())
                return cb

            try:
                t0 = time.perf_counter()
                reqs = []
                for i, ids in enumerate(prompts):
                    t_sub[i] = time.perf_counter()
                    reqs.append(sched.submit(ids, max_tok,
                                             stop_on_eos=False,
                                             on_token=mk_cb(i)))
                    time.sleep(stagger_s)
                for r in reqs:
                    if not r.done.wait(
                            timeout=max(5.0, deadline - time.monotonic())):
                        w["error"] = "deadline inside scenario wave"
                        break
                t_end = time.perf_counter()
            finally:
                stop_sampling.set()
                sampler.join(timeout=5)
                sched.close()
                eng.close()

            done = [r for r in reqs if r.done.is_set() and r.error is None]
            w["n_completed"] = len(done)
            w["n_tokens"] = sum(len(r.tokens) for r in done)
            errs = [r.error for r in reqs if r.error]
            if errs:
                w["request_errors"] = len(errs)
                w.setdefault("error", errs[0][:200])
            dt = t_end - t0
            if dt > 0 and w["n_tokens"]:
                w["agg_tok_per_s"] = round(w["n_tokens"] / dt, 2)
            ttfts = sorted(1e3 * (t_toks[i][0] - t_sub[i]) for i in t_toks)
            w["ttft_ms_p50"] = (round(_pctl(ttfts, 0.5), 1)
                                if ttfts else None)
            w["ttft_ms_p95"] = (round(_pctl(ttfts, 0.95), 1)
                                if ttfts else None)
            # real inter-token latencies from the callback stamps — the
            # A/B's headline latency side (speculation exists to shrink
            # exactly this number)
            itls = sorted(1e3 * (b - a) for ts in t_toks.values()
                          for a, b in zip(ts, ts[1:]))
            w["itl_p50_ms"] = round(_pctl(itls, 0.5), 2) if itls else None
            # latency attribution (runtime/flightrec): the scheduler-side
            # TTFT decomposition per completed request — the
            # continuous-batching throughput number, explained — plus the
            # decode-phase step/preempt/verify split
            attrib: dict = {"queue": [], "pagein": [], "admission": [],
                            "prefill": [], "first_decode": []}
            itl_attrib: dict = {"step": [], "preempt": [], "verify": []}
            rel_errs = []
            for i, r in enumerate(reqs):
                if not (r.done.is_set() and r.error is None):
                    continue
                bd = r.ttft_breakdown()  # the one phase formula (flightrec)
                if bd is None:
                    continue
                attrib["queue"].append(bd["queue_ms"])
                attrib["pagein"].append(bd["pagein_ms"])
                attrib["admission"].append(bd["admission_ms"])
                attrib["prefill"].append(bd["prefill_ms"])
                attrib["first_decode"].append(bd["first_decode_ms"])
                itl_attrib["step"].append(r.ms_decode_steps)
                itl_attrib["preempt"].append(r.ms_preempt)
                itl_attrib["verify"].append(r.ms_verify)
                # reassembly error vs the INDEPENDENTLY measured wall
                # TTFT — this wave's own perf_counter stamps (submit call
                # → first on_token callback), a different clock read at
                # different sites than the scheduler's attribution
                # stamps, so a broken accounting (a dropped phase, a
                # double-charge) shows up here
                if i in t_toks:
                    wall = 1e3 * (t_toks[i][0] - t_sub[i])
                    total = (bd["queue_ms"] + bd["pagein_ms"]
                             + bd["admission_ms"] + bd["prefill_ms"]
                             + bd["first_decode_ms"])
                    if wall > 0:
                        rel_errs.append(abs(total - wall) / wall)
            if attrib["queue"]:
                w["ttft_attrib_ms"] = {
                    k: round(sum(v) / len(v), 2) for k, v in attrib.items()}
                w["itl_attrib_ms"] = {
                    k: round(sum(v) / len(v), 2)
                    for k, v in itl_attrib.items()}
                # phases must reassemble the measured wall TTFT (the
                # ISSUE-7 acceptance bound is 5%; report the worst one)
                w["ttft_attrib_max_rel_err"] = (round(max(rel_errs), 4)
                                                if rel_errs else None)
            if occ:
                w["block_occupancy_peak"] = round(max(occ), 4)
                w["block_occupancy_mean"] = round(sum(occ) / len(occ), 4)
            w["kv_blocks_total"] = int(g_total.value())
            w["kv_blocks_shared_peak"] = int(peaks["shared"])
            w["prefix_reuse_tokens"] = int(reuse.total() - r0)
            drafted = reg.counter(tm.SPEC_DRAFT_TOKENS).total() - d0
            accepted = reg.counter(tm.SPEC_ACCEPTED_TOKENS).total() - a0
            if drafted:
                w["spec_drafted"] = int(drafted)
                w["spec_accepted"] = int(accepted)
                w["spec_accept_rate"] = round(accepted / drafted, 4)
            return w

        out["phase"] = "scenario_run"
        w_off = wave(0)
        out.update(w_off)
        # -- spec on/off A/B over the identical wave -----------------------
        spec_k = _scn_int("DLLAMA_BENCH_SCN_SPEC", 4)
        out["phase"] = "scenario_spec_on"
        w_on = wave(spec_k)
        out["spec_lookup"] = spec_k
        out["spec_ab"] = {
            "off": {k: w_off.get(k)
                    for k in ("agg_tok_per_s", "itl_p50_ms", "ttft_ms_p50",
                              "n_completed")},
            "on": {k: w_on.get(k)
                   for k in ("agg_tok_per_s", "itl_p50_ms", "ttft_ms_p50",
                             "n_completed", "spec_drafted",
                             "spec_accepted", "spec_accept_rate")},
        }
        if w_on.get("error"):
            out.setdefault("error", f"spec-on wave: {w_on['error']}"[:200])
        if w_on.get("agg_tok_per_s"):
            # the A/B's ranked throughput number: tok/s the spec-on wave
            # actually delivered (accepted drafts + verify emissions)
            out["accepted_tok_per_s"] = w_on["agg_tok_per_s"]
        if w_on.get("spec_accept_rate") is not None:
            out["spec_accept_rate"] = w_on["spec_accept_rate"]
        if (w_on.get("itl_p50_ms") is not None
                and w_off.get("itl_p50_ms") is not None):
            out["itl_p50_ms_delta"] = round(
                w_on["itl_p50_ms"] - w_off["itl_p50_ms"], 2)

        # -- tiered KV memory: idle/resume wave (--kv-host-blocks) ---------
        # The capacity shape the tier exists for: S sessions complete a
        # turn and go idle (their KV parks in the cached LRU), the
        # device pool is DELIBERATELY smaller than their combined KV so
        # cold blocks spill to the host mirror, then every session
        # resumes with its history + new text. Reported:
        # `sessions_per_chip` (idle sessions whose KV survived to
        # resume — a block-reuse hit on the resume prompt instead of a
        # full re-prefill) and `resume_ttft_p95_ms` (what a page-in
        # resume costs), both ranked by tools/bench_compare.py and
        # guarded by tools/perf_baseline.py.
        n_sessions = _scn_int("DLLAMA_BENCH_SCN_SESSIONS", 10)
        out["phase"] = "scenario_tiered"

        def tiered_wave() -> dict:
            w: dict = {}
            eng = InferenceEngine(mpath, tpath, tp=1, kv_block_size=block,
                                  kv_host_blocks=8 * n_sessions)
            # 2 slots -> a 2*table_width+1 device pool, well under the
            # sessions' combined KV (the point of the wave)
            sched = BatchScheduler(eng, n_slots=2)
            reg = tm.registry()
            reuse = reg.counter(tm.PREFIX_REUSE_TOKENS)
            spill = reg.counter(tm.KV_SPILL_BLOCKS)
            pagein = reg.counter(tm.KV_PAGEIN_BLOCKS)
            s0, p0 = spill.total(), pagein.total()
            srng = np.random.default_rng(0xC1)
            prompts = [[int(x) for x in srng.integers(1, 200, 4 * block + 4)]
                       for _ in range(n_sessions)]
            try:
                # turn 1: sessions run and retire (go idle)
                reqs = [sched.submit(p, 4, stop_on_eos=False)
                        for p in prompts]
                for r in reqs:
                    if not r.done.wait(
                            timeout=max(5.0, deadline - time.monotonic())):
                        w["error"] = "deadline inside tiered wave"
                        return w
                w["spill_blocks"] = int(spill.total() - s0)
                w["host_used_idle"] = int(
                    reg.gauge(tm.KV_BLOCKS_HOST_USED).value())
                # resumes: sequential so per-session reuse attributes
                hits = 0
                ttfts: list = []
                for i, p in enumerate(prompts):
                    r0 = reuse.total()
                    stamp: list = []
                    t_sub = time.perf_counter()
                    req = sched.submit(
                        p + [int(x) for x in srng.integers(1, 200, 8)],
                        4, stop_on_eos=False,
                        on_token=lambda _t, _p, s=stamp:
                            s.append(time.perf_counter()))
                    if not req.done.wait(
                            timeout=max(5.0, deadline - time.monotonic())):
                        w["error"] = "deadline inside resume wave"
                        return w
                    if req.error is None and reuse.total() - r0 >= block:
                        hits += 1  # KV survived idle: a retained session
                    if stamp:
                        ttfts.append(1e3 * (stamp[0] - t_sub))
                w["sessions_per_chip"] = hits
                w["pagein_blocks"] = int(pagein.total() - p0)
                if ttfts:
                    ttfts.sort()
                    w["resume_ttft_p50_ms"] = round(_pctl(ttfts, 0.5), 1)
                    w["resume_ttft_p95_ms"] = round(_pctl(ttfts, 0.95), 1)
                return w
            finally:
                sched.close()
                eng.close()

        tw = tiered_wave()
        out["tiered"] = tw
        if tw.get("sessions_per_chip") is not None:
            out["sessions_per_chip"] = tw["sessions_per_chip"]
        if tw.get("resume_ttft_p95_ms") is not None:
            out["resume_ttft_p95_ms"] = tw["resume_ttft_p95_ms"]
        if tw.get("error"):
            out.setdefault("error", f"tiered wave: {tw['error']}"[:200])
        out["phase"] = "done"
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_multichip(deadline: float, *, out: dict | None = None) -> dict:
    """``--scenario multichip``: the overlap/wire A/B on a ≥2-device mesh.

    Four engine configs over the same tiny fixture model — the cross of
    ``--comm-overlap {off,auto}`` × ``--wire {f32,q80}`` — each measured
    for greedy decode step time and then profiled for the Eval/Sync split
    and the EXPOSED collective wall (``dllama_comm_exposed_ms``: sync lane
    time not covered by concurrent compute — the quantity the overlapped
    ring merges exist to shrink; runtime/profiling.EvalSyncSplit). The
    per-config analytic wire bytes (qcollectives.wire_traffic_model) show
    the q80 wire's byte shrink next to the time numbers.

    Skip contract: fewer than 2 visible devices emits ``skipped: true`` +
    ``skip_reason`` (tools/bench_compare.py reads that as "no hardware",
    never a regression), the same first-class skip as a dead backend.

    Workload knobs (env): DLLAMA_BENCH_MC_STEPS (24 decode steps per
    config), DLLAMA_BENCH_MC_TP (tp width; default: largest power of two
    ≤ min(n_devices, 4) — the fixture has 4 heads)."""
    import shutil
    import tempfile

    out = {} if out is None else out
    out["phase"] = "scenario_setup"
    import jax

    n_dev = len(jax.devices())
    out["n_devices"] = n_dev
    if n_dev < 2:
        out["skipped"] = True
        out["skip_reason"] = (f"multichip scenario needs >= 2 devices, "
                              f"found {n_dev} (CPU mesh: XLA_FLAGS="
                              f"--xla_force_host_platform_device_count=8)")
        out["phase"] = "done"
        return out
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "tests"))
    import numpy as np

    from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime.engine import InferenceEngine

    tp = _scn_int("DLLAMA_BENCH_MC_TP", 0)
    if tp <= 0:
        tp = 1
        while tp * 2 <= min(n_dev, 4):
            tp *= 2
    steps = _scn_int("DLLAMA_BENCH_MC_STEPS", 24)
    out.update(tp=tp, decode_steps=steps)

    d = tempfile.mkdtemp(prefix="dllama-bench-mc-")
    prev_wire = os.environ.get("DLLAMA_TPU_WIRE")
    try:
        mpath, tpath = os.path.join(d, "m.m"), os.path.join(d, "t.t")
        rng = np.random.default_rng(0xAB)
        write_tiny_model(mpath, tiny_header_params(
            dim=256, hidden_dim=512, n_layers=2, n_heads=4, n_kv_heads=2,
            head_dim=64, vocab_size=268, seq_len=256), rng)
        tfile.write_tfile(tpath, byte_vocab_tokenizer())

        ab: dict = {}
        tokens_by_cfg: dict = {}
        for overlap, wire in (("off", "f32"), ("auto", "f32"),
                              ("off", "q80"), ("auto", "q80")):
            key = f"overlap_{overlap}_{wire}"
            if time.monotonic() > deadline:
                ab[key] = {"error": "deadline before config ran"}
                continue
            out["phase"] = f"config_{key}"
            os.environ["DLLAMA_TPU_WIRE"] = wire
            eng = InferenceEngine(mpath, tpath, tp=tp,
                                  comm_overlap=overlap, temperature=0.0)
            try:
                res = eng.generate([1, 5, 9, 13], steps, stop_on_eos=False)
                n_pred = sum(s.n_tokens for s in res.steps
                             if s.kind == "pred")
                rec: dict = {
                    "n_chunks": eng.cfg.comm_overlap,
                    "decode_tok_per_s": round(res.pred_tok_per_s, 2),
                    "decode_ms_per_step": (round(res.pred_ms / n_pred, 3)
                                           if n_pred else None),
                    "wire_kb_per_token": round(sum(
                        b for _, _, b in eng._wire_traffic) / 1024.0, 3),
                    "wire_ops": sorted({f"{op}/{w}" for op, w, _
                                        in eng._wire_traffic}),
                }
                tokens_by_cfg[key] = res.tokens
                try:
                    split = eng.measure_split()
                    rec["sync_ms"] = round(split.sync_ms, 4)
                    rec["eval_ms"] = round(split.eval_ms, 4)
                    rec["comm_exposed_ms"] = round(split.exposed_ms, 4)
                except Exception as e:  # noqa: BLE001 — keep the rates
                    rec["split_error"] = f"{type(e).__name__}: {e}"[:200]
                ab[key] = rec
            finally:
                eng.close()
        out["ab"] = ab

        # the acceptance invariant, checked where the data is: the f32
        # wire's tokens must be identical overlap-on vs overlap-off
        if ("overlap_off_f32" in tokens_by_cfg
                and "overlap_auto_f32" in tokens_by_cfg):
            out["f32_tokens_identical"] = (
                tokens_by_cfg["overlap_off_f32"]
                == tokens_by_cfg["overlap_auto_f32"])

        # flat fields tools/bench_compare.py ranks
        auto_f32 = ab.get("overlap_auto_f32", {})
        off_f32 = ab.get("overlap_off_f32", {})
        auto_q80 = ab.get("overlap_auto_q80", {})
        if auto_f32.get("decode_tok_per_s"):
            out["decode_tok_per_s"] = auto_f32["decode_tok_per_s"]
        if auto_q80.get("decode_tok_per_s"):
            out["decode_tok_per_s_q80"] = auto_q80["decode_tok_per_s"]
        rates = [c.get("decode_tok_per_s") for c in ab.values()
                 if isinstance(c, dict) and c.get("decode_tok_per_s")]
        if rates:
            out["agg_tok_per_s"] = max(rates)
        if auto_f32.get("comm_exposed_ms") is not None:
            out["comm_exposed_ms"] = auto_f32["comm_exposed_ms"]
        if off_f32.get("comm_exposed_ms") is not None:
            out["comm_exposed_ms_off"] = off_f32["comm_exposed_ms"]
        if ("comm_exposed_ms" in out and "comm_exposed_ms_off" in out):
            out["exposed_overlap_lower"] = (
                out["comm_exposed_ms"] < out["comm_exposed_ms_off"])
        if (auto_q80.get("wire_kb_per_token") is not None
                and auto_f32.get("wire_kb_per_token")):
            out["wire_q80_shrink"] = round(
                auto_f32["wire_kb_per_token"]
                / max(1e-9, auto_q80["wire_kb_per_token"]), 2)
        out["phase"] = "done"
        return out
    finally:
        if prev_wire is None:
            os.environ.pop("DLLAMA_TPU_WIRE", None)
        else:
            os.environ["DLLAMA_TPU_WIRE"] = prev_wire
        shutil.rmtree(d, ignore_errors=True)


def bench_fleet(deadline: float, *, out: dict | None = None) -> dict:
    """``--scenario fleet``: staggered mixed traffic through the fleet
    router (serve/router.py) over N in-process api replicas — each a
    real engine + continuous-batching scheduler + HTTP server on a
    loopback port — with a mid-run replica kill and restart. This is
    the serving topology ROADMAP item 3 describes, measured the way the
    Gemma-on-Cloud-TPU comparison argues for: aggregate tok/s and tail
    TTFT *under churn*, not single-engine throughput. Reported fields
    (tools/bench_compare.py ranks the first three, the counters ride as
    context): ``agg_tok_per_s``, ``ttft_ms_p50``/``ttft_ms_p95``
    (measured at the client through the router, queue + dispatch
    included), and the router's retry/eject/shed counters proving the
    kill/restart schedule actually ran — plus the durable-streams
    verdict on the churn wave: ``streams_resumed`` (mid-stream deaths
    the failover spliced; the happy path is ``streams_resumed > 0,
    streams_dropped = 0``), ``streams_dropped`` (client-visible
    mid-stream errors that survived nothing), and ``resume_p95_ms``
    (detection → first continued token). The kill is aimed: the
    scenario waits (bounded) for a stream that has delivered its first
    chunk and kills the replica its session is bound to, so the death
    lands mid-stream — a pre-first-byte death is an ordinary retry hop
    and would leave the resume path unmeasured.

    After the churn wave, a TWO-TENANT CONTENTION wave runs against the
    restored fleet: a ``flooder`` tenant bursts every request at once
    while a lighter ``interactive`` tenant trickles in behind it, both
    named via ``X-Dllama-Tenant`` and fair-share-scheduled
    (runtime/tenancy weighted per-tenant FIFOs). Reported: per-tenant
    tok/s, queue-wait p95, and sheds under ``tenants``, plus
    ``jain_index`` — Jain's fairness over the wave's per-tenant token
    deltas (higher is better; a flooder that starves the interactive
    tenant drags it toward 0.5).

    Workload knobs (env): DLLAMA_BENCH_FLEET_REPLICAS (3),
    DLLAMA_BENCH_SCN_REQUESTS (18), DLLAMA_BENCH_SCN_MAXTOK (12),
    DLLAMA_BENCH_SCN_STAGGER (0.05 s), DLLAMA_BENCH_TENANT_HEAVY (10),
    DLLAMA_BENCH_TENANT_LIGHT (5).

    DLLAMA_BENCH_FLEET_DISAGG=1 switches the fleet to prefill/decode
    disaggregation: every replica runs the paged pool, replica 0 is
    tagged ``--role prefill``, and the router warms cold prefixes there
    before dispatching decode with an ``X-Dllama-KV-Peer`` pointer — so
    decode replicas pull KV over the checksummed Q80 wire instead of
    recomputing. The churn kill then lands on a DECODE replica (the
    scenario keeps its mid-run death, but the lone prefill stays up so
    the disaggregated path is measured, not just its absence). Extra
    reported fields: ``kv_migrations``/``kv_fallbacks`` (wire outcomes),
    ``kvwire_tx_bytes``/``kvwire_rx_bytes`` (wire volume),
    ``kvmigrate_ms_p50``/``p95`` (per-request TTFT attribution of the
    parked fetch, from the opt-in timing block)."""
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request
    from http.server import ThreadingHTTPServer

    out = {} if out is None else out
    out["phase"] = "scenario_setup"
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "tests"))
    import numpy as np

    from helpers import (byte_vocab_tokenizer, tiny_header_params,
                         write_tiny_model)

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime import slo as slo_mod
    from dllama_tpu.runtime import telemetry as tm
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.serve.api import BatchedApiState, make_handler
    from dllama_tpu.serve.router import FleetRouter, make_router_handler

    n_replicas = _scn_int("DLLAMA_BENCH_FLEET_REPLICAS", 3)
    n_reqs = _scn_int("DLLAMA_BENCH_SCN_REQUESTS", 18)
    max_tok = _scn_int("DLLAMA_BENCH_SCN_MAXTOK", 12)
    stagger_s = float(os.environ.get("DLLAMA_BENCH_SCN_STAGGER", "0.05"))
    disagg = os.environ.get("DLLAMA_BENCH_FLEET_DISAGG", "") not in ("", "0")
    out.update(n_replicas=n_replicas, n_requests=n_reqs)
    if disagg:
        out["disagg"] = True

    d = tempfile.mkdtemp(prefix="dllama-bench-fleet-")
    engines: list = []
    servers: list = []
    states: list = []
    fleet = router_httpd = None
    try:
        mpath, tpath = os.path.join(d, "m.m"), os.path.join(d, "t.t")
        rng = np.random.default_rng(0xF1)
        write_tiny_model(mpath, tiny_header_params(
            dim=256, hidden_dim=512, n_layers=2, n_heads=4, n_kv_heads=2,
            head_dim=64, vocab_size=268, seq_len=256), rng)
        td = byte_vocab_tokenizer()
        td.chat_template = "<|start_header_id|>"  # detected as llama3
        tfile.write_tfile(tpath, td)

        def start_replica(i, port=0):
            # one real engine + batched scheduler + HTTP front per
            # replica — the same stack `python -m dllama_tpu api
            # --batch-slots 2` serves, minus the process boundary.
            # Disagg needs the paged pool on every replica (KV export
            # and import are both block-granular), and replica 0 is
            # the fleet's prefill tier.
            if i >= len(engines):
                engines.append(InferenceEngine(
                    mpath, tpath, tp=1,
                    kv_block_size=16 if disagg else 0))
            state = BatchedApiState(
                engines[i], n_slots=2,
                role="prefill" if disagg and i == 0 else None)
            httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                        make_handler(state))
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            return state, httpd

        out["phase"] = "scenario_engines"
        for i in range(n_replicas):
            state, httpd = start_replica(i)
            states.append(state)
            servers.append(httpd)
        urls = [f"http://127.0.0.1:{s.server_address[1]}" for s in servers]

        out["phase"] = "scenario_router"
        # SLO objectives under which the scenario runs: deliberately
        # loose defaults (CPU-backend-safe — the bench asserts the
        # observatory machinery, the baseline tracks the numbers)
        slo_spec = os.environ.get(
            "DLLAMA_BENCH_SLO",
            "ttft_p95_ms=30000,itl_p50_ms=1000,shed_rate=0.5")
        fleet = FleetRouter(urls, probe_interval_s=0.2, eject_after=2,
                            backoff_min_s=0.2, backoff_max_s=1.0,
                            slo_objectives=slo_mod.parse_slo(slo_spec))
        router_httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                           make_router_handler(fleet))
        threading.Thread(target=router_httpd.serve_forever,
                         daemon=True).start()
        router_url = f"http://127.0.0.1:{router_httpd.server_address[1]}"
        reg = tm.registry()
        up = reg.gauge(tm.ROUTER_REPLICA_UP)
        t_wait = time.monotonic() + 30
        while time.monotonic() < t_wait and not all(
                up.value(replica=r.name) for r in fleet.replicas):
            time.sleep(0.05)
        retries0 = reg.counter(tm.ROUTER_RETRIES).total()
        ejects0 = reg.counter(tm.ROUTER_EJECTS).total()
        shed0 = reg.counter(tm.ROUTER_SHED).total()
        resumed0 = reg.counter(tm.ROUTER_STREAM_RESUMES).total(
            outcome="resumed")
        h_resume = reg.histogram(tm.ROUTER_STREAM_RESUME_MS)
        resume_n0 = h_resume.count()
        mig0 = reg.counter(tm.KVWIRE_MIGRATIONS).total(outcome="migrated")
        fb0 = reg.counter(tm.KVWIRE_MIGRATIONS).total(outcome="fallback")
        txb0 = reg.counter(tm.KVWIRE_TX_BYTES).total()
        rxb0 = reg.counter(tm.KVWIRE_RX_BYTES).total()
        if disagg:
            # the router must have probed the prefill tag before traffic
            # (otherwise the first wave silently measures non-disagg)
            t_wait = time.monotonic() + 15
            while time.monotonic() < t_wait and not any(
                    r.is_prefill() for r in fleet.replicas):
                time.sleep(0.05)
            out["prefill_probed"] = any(r.is_prefill()
                                        for r in fleet.replicas)

        out["phase"] = "scenario_traffic"
        results: dict = {}

        def do_request(i):
            t0 = time.perf_counter()
            stream = i % 2 == 0
            body = {"messages": [{"role": "user",
                                  "content": f"fleet bench {i % 6} "
                                             + "ab" * (i % 4)}],
                    "max_tokens": max_tok, "temperature": 0,
                    "stream": stream, "session_id": f"s{i}"}
            if disagg and not stream:
                body["timing"] = True  # carries kvmigrate_ms attribution
            # registered up front (and mutated in place) so the churn
            # choreography can see which requests are mid-flight
            rec: dict = {"t_sub": t0, "stream": stream}
            results[i] = rec
            try:
                req = urllib.request.Request(
                    router_url + "/v1/chat/completions",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=120) as r:
                    if stream:
                        raw = b""
                        while True:
                            chunk = r.read1(65536)
                            if not chunk:
                                break
                            if "t_first" not in rec \
                                    and b'"delta"' in raw + chunk:
                                rec["t_first"] = time.perf_counter()
                            raw += chunk
                        died = (b"upstream_error" in raw
                                or b'"finish_reason": "error"' in raw)
                        rec["midstream"] = died
                        rec["ok"] = b"[DONE]" in raw and not died
                        rec["tokens"] = (raw.count(b'"delta"')
                                         if rec["ok"] else 0)
                    else:
                        data = json.loads(r.read())
                        rec["t_first"] = time.perf_counter()
                        rec["ok"] = True
                        rec["tokens"] = data["usage"]["completion_tokens"]
                        kvms = data.get("timing", {}).get("kvmigrate_ms")
                        if kvms:
                            rec["kvmigrate_ms"] = kvms
            except urllib.error.HTTPError as e:
                rec.update(ok=False, status=e.code)
            except Exception as e:  # noqa: BLE001 — per-request forensics
                rec.update(ok=False, error=repr(e)[:120])
            rec["t_end"] = time.perf_counter()
            results[i] = rec

        kill_at = max(2, n_reqs // 3)
        restart_at = max(kill_at + 2, (2 * n_reqs) // 3)
        # disagg keeps the churn but aims it at a DECODE replica — the
        # lone prefill tier dying would just measure the (covered
        # elsewhere) no-prefill fallback instead of disaggregation
        ki = (n_replicas - 1) if disagg else 0
        idx_of = {u.split("//", 1)[1]: j for j, u in enumerate(urls)}
        threads: list = []
        t0 = time.perf_counter()
        for i in range(n_reqs):
            if time.monotonic() > deadline:
                out["error"] = "deadline inside traffic wave"
                break
            if i == kill_at:
                # the churn event: a replica dies mid-traffic — new
                # connections refused, its scheduler fails in-flight
                # work. Aim it MID-STREAM: wait (bounded) for a stream
                # that has delivered its first chunk and kill the
                # replica its session is bound to — a pre-first-byte
                # death is a plain retry hop, not a durable-stream
                # resume, and would leave the failover path unmeasured
                out["phase"] = "scenario_kill"
                t_aim = time.monotonic() + 30
                while time.monotonic() < min(t_aim, deadline):
                    with fleet._lock:
                        aff = {k: v.name
                               for k, v in fleet._affinity.items()}
                    live = [j for j, r in list(results.items())
                            if r.get("stream") and "t_first" in r
                            and "t_end" not in r
                            and f"sid:s{j}" in aff
                            and (not disagg
                                 or idx_of[aff[f"sid:s{j}"]] != 0)]
                    if live:
                        ki = idx_of[aff[f"sid:s{live[0]}"]]
                        break
                    time.sleep(0.02)
                servers[ki].shutdown()
                servers[ki].server_close()
                states[ki].close(drain_s=0.0)
            if i == restart_at:
                out["phase"] = "scenario_restart"
                state, httpd = start_replica(
                    ki, port=int(urls[ki].rsplit(":", 1)[1]))
                states[ki], servers[ki] = state, httpd
            th = threading.Thread(target=do_request, args=(i,))
            th.start()
            threads.append(th)
            time.sleep(stagger_s)
        for th in threads:
            th.join(timeout=max(5.0, deadline - time.monotonic()))
        t_end = time.perf_counter()

        out["phase"] = "scenario_report"
        done = [r for r in results.values() if r.get("ok")]
        out["n_completed"] = len(done)
        out["n_failed"] = sum(1 for r in results.values()
                              if not r.get("ok") and not r.get("midstream"))
        out["n_midstream_error"] = sum(1 for r in results.values()
                                       if r.get("midstream"))
        out["n_tokens"] = sum(r["tokens"] for r in done)
        dt = t_end - t0
        if dt > 0 and out["n_tokens"]:
            out["agg_tok_per_s"] = round(out["n_tokens"] / dt, 2)
        ttfts = sorted(1e3 * (r["t_first"] - r["t_sub"])
                       for r in done if "t_first" in r)
        out["ttft_ms_p50"] = round(_pctl(ttfts, 0.5), 1) if ttfts else None
        out["ttft_ms_p95"] = round(_pctl(ttfts, 0.95), 1) if ttfts else None
        out["router_retries"] = int(reg.counter(tm.ROUTER_RETRIES).total()
                                    - retries0)
        out["router_ejects"] = int(reg.counter(tm.ROUTER_EJECTS).total()
                                   - ejects0)
        out["router_shed"] = int(reg.counter(tm.ROUTER_SHED).total()
                                 - shed0)
        # durable streams under churn: the kill lands mid-stream, so
        # the router's failover must splice continuations — resumed
        # streams finish token-exactly (they count toward n_completed),
        # dropped ones surface as the client-visible mid-stream error
        out["streams_resumed"] = int(reg.counter(
            tm.ROUTER_STREAM_RESUMES).total(outcome="resumed") - resumed0)
        out["streams_dropped"] = out["n_midstream_error"]
        out["resume_p95_ms"] = (round(h_resume.quantile(0.95), 1)
                                if h_resume.count() > resume_n0 else None)
        if disagg:
            # wire outcomes + volume: what the disaggregation actually
            # moved instead of recomputing, and what fell back
            out["kv_migrations"] = int(reg.counter(
                tm.KVWIRE_MIGRATIONS).total(outcome="migrated") - mig0)
            out["kv_fallbacks"] = int(reg.counter(
                tm.KVWIRE_MIGRATIONS).total(outcome="fallback") - fb0)
            out["kvwire_tx_bytes"] = int(reg.counter(
                tm.KVWIRE_TX_BYTES).total() - txb0)
            out["kvwire_rx_bytes"] = int(reg.counter(
                tm.KVWIRE_RX_BYTES).total() - rxb0)
            kvms = sorted(r["kvmigrate_ms"] for r in done
                          if r.get("kvmigrate_ms"))
            out["kvmigrate_ms_p50"] = (round(_pctl(kvms, 0.5), 1)
                                       if kvms else None)
            out["kvmigrate_ms_p95"] = (round(_pctl(kvms, 0.95), 1)
                                       if kvms else None)
        # the restart's re-admission, telemetry-asserted: the breaker
        # must bring the killed replica back before the scenario ends
        t_wait = time.monotonic() + 15
        killed = fleet.replicas[ki].name
        while time.monotonic() < t_wait \
                and not up.value(replica=killed):
            time.sleep(0.1)
        out["readmitted"] = bool(up.value(replica=killed))
        # two-tenant contention wave: a flooding tenant bursts the
        # restored fleet while a light interactive tenant trickles in
        # behind it — fair-share admission (weighted per-tenant FIFOs,
        # runtime/tenancy) must keep the light tenant served. In-process
        # fleet means ONE shared tenant registry across the router and
        # every replica, so per-tenant totals are read directly.
        # Reported: per-tenant tok/s + queue-wait p95 + sheds, and
        # ``jain_index`` — Jain's fairness over the wave's per-tenant
        # decode-token deltas (1.0 = served proportionally to demand;
        # a starved light tenant drags it toward 1/n). Knobs:
        # DLLAMA_BENCH_TENANT_HEAVY (10) / DLLAMA_BENCH_TENANT_LIGHT (5).
        out["phase"] = "scenario_tenants"
        from dllama_tpu.runtime import tenancy as tn
        treg = tn.registry()
        treg.set_limits(tn.parse_limits(
            {"flooder": {"weight": 1.0},
             "interactive": {"weight": 4.0}}))
        n_heavy = _scn_int("DLLAMA_BENCH_TENANT_HEAVY", 10)
        n_light = _scn_int("DLLAMA_BENCH_TENANT_LIGHT", 5)
        snap0 = treg.snapshot()["tenants"]
        tok0 = {t: st.get("decode_tokens", 0)
                for t, st in snap0.items()}
        t_results: dict = {}

        def tenant_request(tag, tenant, i):
            rec: dict = {"t_sub": time.perf_counter()}
            t_results[tag] = rec
            body = {"messages": [{"role": "user",
                                  "content": f"tenant {tenant} wave {i}"}],
                    "max_tokens": max_tok, "temperature": 0,
                    "stream": False}
            try:
                req = urllib.request.Request(
                    router_url + "/v1/chat/completions",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json",
                             "X-Dllama-Tenant": tenant})
                with urllib.request.urlopen(req, timeout=120) as r:
                    json.loads(r.read())
                    rec["ok"] = True
            except Exception as e:  # noqa: BLE001 — per-request forensics
                rec.update(ok=False, error=repr(e)[:120])
            rec["t_end"] = time.perf_counter()

        t_threads: list = []
        tw0 = time.perf_counter()
        for i in range(n_heavy):  # the flood: all at once
            th = threading.Thread(target=tenant_request,
                                  args=(f"h{i}", "flooder", i))
            th.start()
            t_threads.append(th)
        for i in range(n_light):  # the interactive trickle
            th = threading.Thread(target=tenant_request,
                                  args=(f"l{i}", "interactive", i))
            th.start()
            t_threads.append(th)
            time.sleep(stagger_s)
        for th in t_threads:
            th.join(timeout=max(5.0, deadline - time.monotonic()))
        tw = time.perf_counter() - tw0
        snap1 = treg.snapshot()["tenants"]
        tenant_toks: dict = {}
        out["tenants"] = {}
        for tenant in ("flooder", "interactive"):
            st = snap1.get(tenant, {})
            toks = st.get("decode_tokens", 0) - tok0.get(tenant, 0)
            tenant_toks[tenant] = toks
            qw = st.get("queue_wait_ms", {})
            out["tenants"][tenant] = {
                "tok_per_s": round(toks / tw, 2) if tw > 0 else None,
                "queue_wait_ms_p95": (round(qw["p95"], 1)
                                      if qw.get("n") else None),
                "sheds": sum(st.get("sheds", {}).values())}
        out["jain_index"] = round(
            tn.jain_index(tenant_toks.values()), 4)
        # the SLO observatory's verdict on the run: per-objective
        # compliance + worst burn, plus the two flat fields the
        # compare/baseline tools rank (slo_compliance_min: 1.0 = every
        # objective met, 0.0 = at least one violated; slo_worst_burn:
        # the hottest error-budget burn across objectives × windows)
        ev = fleet.slo.evaluate()
        out["slo"] = {
            name: {"threshold": rec["threshold"],
                   "estimate": round(rec["estimate"], 4),
                   "compliant": rec["compliant"],
                   "burn": {w: round(b, 3)
                            for w, b in rec["burn"].items()}}
            for name, rec in ev["objectives"].items()}
        out["slo_compliance_min"] = min(
            (1.0 if rec["compliant"] else 0.0)
            for rec in ev["objectives"].values())
        out["slo_worst_burn"] = round(max(
            max(rec["burn"].values())
            for rec in ev["objectives"].values()), 3)
        out["phase"] = "done"
        return out
    finally:
        if router_httpd is not None:
            router_httpd.shutdown()
            router_httpd.server_close()
        if fleet is not None:
            fleet.close()
        for httpd in servers:
            try:
                httpd.shutdown()
                httpd.server_close()
            except OSError:
                pass  # the killed replica's server is already closed
        for state in states:
            state.close(drain_s=0.0)
        for eng in engines:
            eng.close()
        shutil.rmtree(d, ignore_errors=True)


def bench_eval(deadline: float, *, out: dict | None = None) -> dict:
    """``--scenario eval``: the quality observatory's throughput-and-
    parity scenario. Scores the committed fixture
    (tests/goldens/eval_tiny.jsonl) teacher-forced through the REAL
    serving stack (runtime/evalharness) under every config in
    telemetry.EVAL_CONFIGS — the engine oracle plus dense/paged/
    paged_spec continuous batching — and reports, per config,
    ``eval_tok_per_s`` (scored positions per wall second; ranked
    higher-better by tools/bench_compare.py) beside ``perplexity``
    (ranked lower-better) and the bit-exact ``total_nll_hex``. The
    headline carries the batched ``eval_tok_per_s`` and a
    ``parity_drift`` flag: any exact-parity pair (telemetry.EVAL_PARITY)
    whose totals differ bit-from-bit is a numerics bug, not a quality
    tradeoff, and tools/bench_compare.py calls it out as such.

    Workload knobs (env): DLLAMA_BENCH_SCN_SLOTS (4),
    DLLAMA_BENCH_KV_BLOCK (16)."""
    import shutil
    import tempfile

    out = {} if out is None else out
    out["phase"] = "scenario_setup"
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "tests"))
    import numpy as np

    from helpers import (byte_vocab_tokenizer, tiny_header_params,
                         write_tiny_model)

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime import evalharness
    from dllama_tpu.runtime import telemetry as tm
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.runtime.serving import BatchScheduler

    n_slots = _scn_int("DLLAMA_BENCH_SCN_SLOTS", 4)
    block = _scn_int("DLLAMA_BENCH_KV_BLOCK", 16)
    out.update(n_slots=n_slots, kv_block_size=block, dataset="eval_tiny")
    seqs = evalharness.load_dataset(
        os.path.join(here, "tests", "goldens", "eval_tiny.jsonl"))
    out["n_seqs"] = len(seqs)

    d = tempfile.mkdtemp(prefix="dllama-bench-eval-")
    try:
        mpath, tpath = os.path.join(d, "m.m"), os.path.join(d, "t.t")
        rng = np.random.default_rng(0xC0)
        write_tiny_model(mpath, tiny_header_params(
            dim=256, hidden_dim=512, n_layers=2, n_heads=4, n_kv_heads=2,
            head_dim=64, vocab_size=268, seq_len=256), rng)
        tfile.write_tfile(tpath, byte_vocab_tokenizer())

        out["phase"] = "scenario_eval"
        configs: dict = {}
        for config in tm.EVAL_CONFIGS:
            kw = {}
            if config in ("paged", "paged_spec"):
                kw["kv_block_size"] = block
            if config == "paged_spec":
                kw["spec_lookup"] = 4
            eng = InferenceEngine(mpath, tpath, tp=1, **kw)
            sched = None
            try:
                if config == "single":
                    run = evalharness.run_eval(seqs, dataset="eval_tiny",
                                               config=config, engine=eng)
                else:
                    sched = BatchScheduler(eng, n_slots=n_slots)
                    run = evalharness.run_eval(seqs, dataset="eval_tiny",
                                               config=config, sched=sched)
            finally:
                if sched is not None:
                    sched.close()
                eng.close()
            configs[config] = {k: run[k] for k in (
                "n_tokens", "perplexity", "total_nll_hex",
                "eval_tok_per_s", "wall_s")}
        out["configs"] = configs
        # the ranked numbers: batched eval throughput (paged — the config
        # production promotion would run) and the dataset perplexity
        out["eval_tok_per_s"] = configs["paged"]["eval_tok_per_s"]
        out["perplexity"] = round(configs["paged"]["perplexity"], 6)
        out["total_nll_hex"] = configs["paged"]["total_nll_hex"]
        out["parity_drift"] = any(
            configs[a]["total_nll_hex"] != configs[b]["total_nll_hex"]
            for a, b in tm.EVAL_PARITY
            if a in configs and b in configs)
        out["phase"] = "done"
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


SCENARIOS = ("continuous", "multichip", "fleet", "eval")
SCENARIO_FNS = {"continuous": bench_continuous, "multichip": bench_multichip,
                "fleet": bench_fleet, "eval": bench_eval}


def _result_skeleton(metric: str) -> dict:
    """The one-line emit contract's required fields + the git stamp —
    shared by main() and scenario_main so the shape cannot drift."""
    result: dict = {
        "metric": metric,
        "value": 0.0,
        "unit": "tok/s",
        "vs_baseline": 0.0,
        "error": None,
    }
    try:
        result["git"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or None
    except Exception:  # noqa: BLE001 — traceability only
        result["git"] = None
    return result


def _mark_skipped(result: dict, detail: str, attempts: list,
                  t_start: float) -> None:
    """Stamp the first-class skip contract (no live measurement ran —
    tools/bench_compare.py must read this as 'no hardware', never as a
    regression) — shared by every no-backend emit path."""
    result["skipped"] = True
    result["skip_reason"] = f"backend unavailable: {detail}"
    result["error"] = f"backend unavailable: {detail}"
    result["probe_attempts"] = attempts
    result["elapsed_s"] = round(time.monotonic() - t_start, 1)


def _stage_cache_env() -> None:
    """Persistent XLA compile cache for the measurement children —
    amortizes compiles across stages and across bench runs."""
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/dllama-xla-cache-bench")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")


def scenario_main(name: str) -> None:
    """``bench.py --scenario <name>`` entry: probe the backend, run the
    serving scenario in an isolated stage child (same wedge containment as
    the preset stages), and print exactly ONE JSON line whose per-stage
    fields tools/bench_compare.py knows how to diff."""
    t_start = time.monotonic()
    result = _result_skeleton("eval_tok_per_s" if name == "eval"
                              else f"{name}_agg_tok_per_s")
    if name not in SCENARIOS:
        result["error"] = f"unknown scenario {name!r} (have {SCENARIOS})"
        emit(result)
        return

    force_platform = os.environ.get("DLLAMA_BENCH_PLATFORM")
    if force_platform:
        os.environ["JAX_PLATFORMS"] = force_platform
    attempts: list = []
    ok, detail = probe_backend(force_platform, attempts)
    if not ok:
        _mark_skipped(result, detail, attempts, t_start)
        emit(result)
        return
    try:
        info = json.loads(detail)
    except (ValueError, IndexError):
        info = {"platform": "unknown", "kind": "unknown", "n": 0}
    result["platform"] = info.get("platform")
    result["device_kind"] = info.get("kind")
    _stage_cache_env()
    if (name == "multichip" and info.get("platform") == "cpu"
            and "xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        # the CPU backend exposes ONE device by default; the multichip A/B
        # needs a mesh — give the stage child the 8-device virtual mesh
        # the test tier uses (a real TPU slice is unaffected)
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_"
                                     "count=8").strip()

    res = run_stage(name, STAGE_DEADLINE_S)
    result["stages"] = {name: res}
    if res.get("skipped"):
        # the scenario itself declared a first-class skip (e.g. a single
        # device): propagate it so comparisons read "no hardware"
        result["skipped"] = True
        result["skip_reason"] = res.get("skip_reason")
        result["error"] = res.get("skip_reason")
    elif res.get("agg_tok_per_s"):
        result["value"] = res["agg_tok_per_s"]
    elif res.get("eval_tok_per_s"):
        # the eval scenario's headline is scored positions per second
        result["value"] = res["eval_tok_per_s"]
    else:
        result["error"] = res.get("error", "scenario did not measure")
    result["elapsed_s"] = round(time.monotonic() - t_start, 1)
    emit(result)


def _find_fallback_capture():
    """Newest VALID banked capture, for emitting when the live chip is down.

    The round-4 failure this guards against: the chip wedged hours before the
    driver's end-of-round bench run, so BENCH_r04.json recorded only dead
    probes even though a clean fetch-forced capture existed on disk.  Search
    order: watcher captures (bench_results/capture_*/ and their tracked
    mirrors under capture_artifacts/), newest first, then committed
    BENCH_r*_manual.json snapshots.  A capture is valid iff

    * its directory has no ``INVALID`` marker (rounds 1-3 enqueue-rate
      captures are marked),
    * it is not itself a fallback emission (no recursive staleness), and
    * at least one stage carries BOTH ``fetch_rtt_ms`` (proof the
      fetch-forced methodology produced it) and a measured decode number, and
    * its top-level headline ``value`` is nonzero (a capture whose headline
      stage failed is passed over for an older one that measured).

    Returns ``(data, path)`` or ``None``."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    cands = []
    for pat in ("bench_results/capture_*/BENCH_live.json",
                "capture_artifacts/*/BENCH_live.json"):
        for p in glob.glob(os.path.join(here, pat)):
            d = os.path.dirname(p)
            if os.path.exists(os.path.join(d, "INVALID")):
                continue
            # a tracked mirror (capture_artifacts/<ts>) is copied at capture
            # time, BEFORE any post-hoc invalidation can land in it — consult
            # its bench_results sibling's marker too
            if pat.startswith("capture_artifacts"):
                sib = os.path.join(here, "bench_results",
                                   f"capture_{os.path.basename(d)}")
                if os.path.exists(os.path.join(sib, "INVALID")):
                    continue
            cands.append(p)
    # capture dirs are named capture_<utc-ts> (bench_results) or bare
    # <utc-ts> (tracked mirrors): strip the prefix so the sort compares
    # timestamps, not the 'capture_' literal
    cands.sort(key=lambda p: os.path.basename(os.path.dirname(p))
               .removeprefix("capture_"), reverse=True)
    def _round_no(p: str) -> int:
        # BENCH_r<NN>_manual.json — numeric sort (lexicographic would rank
        # r9 above r10)
        import re

        m = re.search(r"BENCH_r(\d+)", os.path.basename(p))
        return int(m.group(1)) if m else -1

    cands += sorted(glob.glob(os.path.join(here, "BENCH_r*_manual.json")),
                    key=_round_no, reverse=True)
    for p in cands:
        try:
            with open(p) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(data, dict) or "fallback" in data:
            continue
        stages = data.get("stages") or {}
        if not any(isinstance(s, dict) and s.get("fetch_rtt_ms")
                   and s.get("decode_tok_per_s") for s in stages.values()):
            continue
        if data.get("value"):
            return data, p
    return None


def main() -> None:
    t_start = time.monotonic()
    result = _result_skeleton("decode_tok_per_s_llama8b_q40_1chip")

    force_platform = os.environ.get("DLLAMA_BENCH_PLATFORM")  # e.g. "cpu" self-test
    if force_platform:
        os.environ["JAX_PLATFORMS"] = force_platform

    attempts: list = []
    ok, detail = probe_backend(force_platform, attempts)
    if not ok:
        # late-window retry: the round-2 hang looked like a transient
        # backend-side lock; give the chip one more chance after a long wait
        wait = min(300.0, max(0.0, STAGE_DEADLINE_S / 2))
        time.sleep(wait)
        info = probe_once(force_platform, attempts)
        if info is not None:
            ok, detail = True, info
    if not ok:
        fb = _find_fallback_capture()
        if fb is not None:
            data, path = fb
            here = os.path.dirname(os.path.abspath(__file__))
            # first-class skip marker: the LIVE measurement did not run —
            # the numbers below are a re-emitted banked capture, so a
            # comparison tool must read this as "no hardware", never as a
            # regression or an improvement (tools/bench_compare.py)
            data["skipped"] = True
            data["skip_reason"] = (f"backend unavailable: {detail}; "
                                   f"re-emitting banked capture "
                                   f"{os.path.relpath(path, here)}")
            data["fallback"] = {
                "source": os.path.relpath(path, here),
                "live_probe_error": detail,
                "probe_attempts": attempts,
                "note": ("backend unavailable at bench time; emitting the "
                         "newest valid fetch-forced capture banked by "
                         "tools/chip_watch.sh (VERDICT r4 next #4)"),
            }
            data["elapsed_s"] = round(time.monotonic() - t_start, 1)
            emit(data)
            return
        _mark_skipped(result, detail, attempts, t_start)
        result["env"] = {
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS"),
            "accel_devices": sorted(
                f for f in os.listdir("/dev") if f.startswith(("accel", "vfio"))
            ) if os.path.isdir("/dev") else [],
        }
        emit(result)
        return

    try:
        info = json.loads(detail)
    except (ValueError, IndexError):
        info = {"platform": "unknown", "kind": "unknown", "n": 0}
    result["platform"] = info.get("platform")
    result["device_kind"] = info.get("kind")
    if len(attempts) > 1:  # flaky init is itself a finding worth recording
        result["probe_attempts"] = attempts

    # the parent stays jax-free: every measurement runs in a --stage child
    # (stage_child re-pins jax_platforms there; sitecustomize would clobber
    # a bare env var)
    _stage_cache_env()

    # promoted serving config (tools/promote_config.py, written when an
    # on-chip A/B showed a combo beating `auto` by >=10%): apply its env
    # knobs to the measurement children, with full provenance in the line.
    # Explicitly-set env vars win — a sweep/debug run isn't overridden.
    promo_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "bench_promoted.json")
    if os.environ.get("DLLAMA_BENCH_NO_PROMO"):
        promo_path = ""  # isolation runs (e.g. the f8-KV twin) opt out
    if promo_path and os.path.exists(promo_path):
        try:
            with open(promo_path) as f:
                promo = json.load(f)
            applied = {}
            for var, val in (promo.get("env") or {}).items():
                if var not in os.environ:
                    os.environ[var] = str(val)
                    applied[var] = str(val)
            result["promoted_config"] = {
                "combo": promo.get("combo"), "applied_env": applied,
                "evidence": promo.get("evidence")}
        except (OSError, ValueError) as e:
            result["promoted_config"] = {"error": f"{type(e).__name__}: {e}"}

    on_tpu = "tpu" in str(info.get("kind", "")).lower() or info.get("platform") in ("tpu", "axon")
    tflops, gbps = detect_specs(str(info.get("kind", "")))

    # 1b FIRST: the cheap preset banks a real number before the 8B shape —
    # which once OOM-wedged the chip for the rest of the window — ever runs.
    specs = ["1b", "8b", "8b@b16", "1b@s8k"] if on_tpu else ["tiny"]
    if os.environ.get("DLLAMA_BENCH_PRESET"):
        specs = os.environ["DLLAMA_BENCH_PRESET"].split(",")
    bad = [s for s in specs
           if s.partition("@")[0] not in PRESETS
           or s.partition("@")[2] not in ("", "b16", "s8k")]
    if bad:
        result["error"] = f"unknown preset(s) {bad}"
        emit(result)
        return

    # the window scales with the stage list: one STAGE_DEADLINE_S covers the
    # first stage (probe + compiles dominate it) and each further stage adds
    # headroom, so a slow early stage can't silently starve the later ones
    deadline = (t_start + PROBE_TIMEOUT_S + STAGE_DEADLINE_S
                + 300.0 * max(0, len(specs) - 1))

    # Watchdog: the per-stage deadline checks can't fire while blocked INSIDE
    # a jax call (backend init / compile hang — the exact round-1 failure).
    # A daemon timer force-emits the JSON line and exits 0 at the deadline.
    import threading

    _wd_done = threading.Event()

    def _watchdog():
        # poll instead of a fixed Timer: time spent WAITING on the chip
        # lock (legitimate contention with a concurrent capture, not a
        # wedge) extends the effective deadline
        while not _wd_done.wait(10.0):
            if time.monotonic() > deadline + _LOCK_WAIT_TOTAL[0] + 60:
                break
        if _wd_done.is_set():
            return
        # kill in-flight stage children FIRST: os._exit releases the chip
        # lock while an orphan would keep its model staged — the exact
        # double-residency wedge the lock exists to prevent
        for ch in list(_LIVE_CHILDREN):
            try:
                ch.kill()
            except Exception:  # noqa: BLE001
                pass
        try:
            result.setdefault("stages", {})
            result["error"] = (result.get("error")
                               or f"watchdog: exceeded {STAGE_DEADLINE_S}s inside a stage")
            result["elapsed_s"] = round(time.monotonic() - t_start, 1)
            # deep-copy first: the main thread mutates the shared stage dicts
            # and a mid-encode mutation must not kill the line we exist to emit
            try:
                snapshot = json.loads(json.dumps(result, default=str))
            except Exception:  # noqa: BLE001
                snapshot = {"metric": result.get("metric"), "value": 0.0,
                            "unit": "tok/s", "vs_baseline": 0.0,
                            "error": result.get("error")}
            emit(snapshot)
        finally:
            os._exit(0)

    wd = threading.Thread(target=_watchdog, daemon=True)
    wd.start()

    stages: dict = {}
    result["stages"] = stages  # shared upfront: the watchdog emits partials
    for spec in specs:
        remaining = deadline - time.monotonic()
        if remaining < 60:
            stages[spec] = {"error": "window exhausted before stage ran"}
            continue
        base = spec.partition("@")[0]
        if ("@" in spec and base in stages
                and "decode_tok_per_s" not in stages[base]):
            # the base preset ran THIS invocation and failed — don't repeat
            # the failure at batch 16 (an explicit @b16-only run still runs)
            stages[spec] = {"error": "skipped: base preset did not measure"}
            continue
        stages[spec] = run_stage(spec, min(STAGE_DEADLINE_S, remaining))

    # headline preference: the 8B BASELINE shape when it measured, else the
    # largest preset that did (a banked 1b number beats a zero)
    head = next((s for s in ("8b", "1b", "tiny")
                 if stages.get(s, {}).get("decode_tok_per_s")),
                specs[0].partition("@")[0])
    head_res = stages.get(head, {})
    n_params = matmul_param_count(head)
    # bytes/weight by the measured representation (the stage records it):
    # Q40 planes = 1B codes + f32/32 scales; bf16 dense = 2B
    wrepr = head_res.get("weights", "q40")
    weight_gb = n_params * (2.0 if wrepr == "bf16" else 1 + 4 / 32) / 1e9
    if head_res.get("decode_tok_per_s"):
        v = head_res["decode_tok_per_s"]
        result["value"] = v
        result["metric"] = f"decode_tok_per_s_llama{head}_{wrepr}_1chip"
        result["vs_baseline"] = round(v / NORTH_STAR_TOK_S, 4)
        # roofline + efficiency context: the ceilings come from the hw_probe
        # file when one exists (honest measured silicon) and the nameplate
        # table otherwise — the section names its source either way
        # (runtime/roofline, loaded jax-free by file path)
        roofmod = _roofline_mod()
        ceil = roofmod.load_ceilings(device_kind=str(info.get("kind", "")))
        result["roofline"] = roofmod.rate_roofline(v, weight_gb, ceil)
        # per program-FAMILY fractions (decode vs prefill vs paged): the
        # paged family prices the same weight stream, so its lower
        # fraction IS the visible cost of the block-table gather/kernel
        result["roofline"]["families"] = roofmod.rate_roofline_families(
            head_res, weight_gb, n_params, ceil)
        # legacy flat fields (tools/analyze_capture.py and older captures
        # read these; same numbers as the section, nameplate-based)
        result["roofline_decode_tok_per_s"] = round(gbps / weight_gb, 1)
        result["hbm_util_decode"] = round(v * weight_gb / gbps, 4)
        if head_res.get("prefill_tok_per_s"):
            result["prefill_mfu"] = round(
                head_res["prefill_tok_per_s"] * 2 * n_params / (tflops * 1e12), 4)
    else:
        result["error"] = head_res.get("error", "no result")

    # chip is alive: spend any remaining window on the @pytest.mark.tpu tier
    # (the error-bound claims that have never run on hardware) and embed the
    # outcome — VERDICT round-2 next #1.
    if on_tpu and time.monotonic() < deadline and not result.get("error"):
        budget = min(420.0, deadline + 120 - time.monotonic())
        try:
            env = dict(os.environ, DLLAMA_TESTS_TPU="1")
            env.pop("JAX_PLATFORMS", None)
            env.pop("XLA_FLAGS", None)
            t_lk = time.monotonic()
            with _chip_lock():  # the tier stages real models on the chip
                _LOCK_WAIT_TOTAL[0] += time.monotonic() - t_lk
                tp = subprocess.run(
                    [sys.executable, "-m", "pytest", "tests", "-m", "tpu", "-q",
                     "--no-header", "-p", "no:cacheprovider"],
                    capture_output=True, timeout=budget,
                    cwd=os.path.dirname(os.path.abspath(__file__)), env=env)
            result["tpu_test_tier"] = {
                "rc": tp.returncode,
                "tail": _tail(tp.stdout)[-400:],
            }
        except subprocess.TimeoutExpired as e:
            result["tpu_test_tier"] = {"rc": None, "timeout_s": budget,
                                       "tail": _tail(e.stdout)[-400:]}
        except Exception as e:  # noqa: BLE001
            result["tpu_test_tier"] = {"rc": None, "tail": f"{type(e).__name__}: {e}"}

    result["elapsed_s"] = round(time.monotonic() - t_start, 1)
    _wd_done.set()
    emit(result)


def baseline_main(argv: list) -> int:
    """``bench.py --baseline {check,update}``: the perf-regression
    sentinel (tools/perf_baseline.py) wrapped around a bench run.

    Without ``--result FILE`` the bench runs live in a SUBPROCESS (main's
    watchdog force-exits its process on a wedge — the comparison must
    survive that) and its one emitted JSON line is the comparison side.
    ``check`` exits 1 naming every regressed metric; a skipped run or a
    run with no overlapping metrics is first-class NO EVIDENCE and exits
    0 (so ``make perf-check`` stays green on hardware-less runners
    without pretending it verified anything). ``update`` records the
    result as the new ``PERF_BASELINE.json``."""
    import argparse

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "tools"))
    import perf_baseline

    ap = argparse.ArgumentParser(prog="bench.py --baseline")
    ap.add_argument("mode", choices=("check", "update"))
    ap.add_argument("--result", default=None,
                    help="compare/record this bench JSON instead of "
                         "running a live bench")
    ap.add_argument("--baseline-file",
                    default=os.path.join(here, "PERF_BASELINE.json"))
    ap.add_argument("--name", default="local",
                    help="baseline name (update mode)")
    args = ap.parse_args(argv)

    if args.result:
        try:
            bench = perf_baseline.load_bench_json(args.result)
        except (OSError, ValueError) as e:
            # filesystem error, not a perf verdict: named rc 2 (the
            # regression exit code stays reserved for real regressions)
            print(f"❌ result file unusable: {e}", file=sys.stderr)
            return 2
    else:
        proc = subprocess.run([sys.executable,
                               os.path.join(here, "bench.py")],
                              capture_output=True, text=True, cwd=here)
        bench = perf_baseline.last_json_line(proc.stdout)
        if bench is None:
            print(f"❌ live bench emitted no JSON line (rc={proc.returncode})"
                  f"\n{_tail(proc.stderr)}", file=sys.stderr)
            return 2

    if args.mode == "update":
        try:
            doc = perf_baseline.make_baseline(bench, args.name,
                                              source=args.result or "live")
        except ValueError as e:
            # a skipped/empty run must never OVERWRITE a real baseline
            print(f"❌ not updating baseline: {e}", file=sys.stderr)
            return 2
        perf_baseline.write_baseline(doc, args.baseline_file)
        return 0

    try:
        with open(args.baseline_file, encoding="utf-8") as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        # unreadable OR corrupt (truncated write, merge-conflict markers):
        # a named rc-2, never a traceback that CI reads as a regression
        print(f"❌ baseline file unusable: {e}", file=sys.stderr)
        return 2
    cmp = perf_baseline.compare(bench, baseline)
    print(perf_baseline.format_report(cmp), file=sys.stderr)
    emit({"metric": "baseline_check", "baseline": baseline.get("name"),
          "verdict": cmp["verdict"],
          "regressed": [r["metric"] for r in cmp["regressions"]],
          "result": cmp})
    return 1 if cmp["regressions"] else 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        stage_child(sys.argv[2])
    elif len(sys.argv) >= 3 and sys.argv[1] == "--scenario":
        scenario_main(sys.argv[2])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--baseline":
        sys.exit(baseline_main(sys.argv[2:]))
    else:
        main()
