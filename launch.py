#!/usr/bin/env python
"""Model zoo launcher (reference: launch.py) — see dllama_tpu/zoo.py."""

from dllama_tpu.zoo import main

if __name__ == "__main__":
    raise SystemExit(main())
