"""Measure the batched-serving scheduler's HOST cost per tick.

Round-3's verdict (weak #5) flagged ``BatchedGenerator.step`` as a
potential host-side bottleneck — per-token Python under a lock with numpy
marshalling for all slots — and noted it was unmeasured.  This tool
separates the host loop from device compute on the CPU backend (where the
tiny model's dispatch is cheap and timing is honest):

  raw dispatch   the ragged sampled_steps program alone, B = n_slots
  generator      BatchedGenerator.step() with all slots busy on long
                 prompts (admission excluded)

host overhead per tick = generator ms - raw ms.  The budget it must fit
inside on TPU is the weight-streaming time of a real model (e.g. ~29 ms
for the 8B shape), times --decode-chunk when chunked ticks amortize it.

Usage: python tools/serving_hostloop.py [n_slots] [ticks]
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

# host-loop cost is a CPU-side question; force the cpu backend (the image's
# sitecustomize rewrites JAX_PLATFORMS at interpreter start, so a setdefault
# here would lose and the import would block on a wedged tunnel). Override
# with DLLAMA_HOSTLOOP_PLATFORM to measure on the real chip.
_platform = os.environ.get("DLLAMA_HOSTLOOP_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform


SEQ_LEN = 256
PROMPT_LEN = 28


def main() -> None:
    n_slots = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 200
    # a slot retires at the seq_len cap and ticks on an empty pool cost ~0,
    # which would silently deflate the measured host cost — cap instead
    max_ticks = SEQ_LEN - PROMPT_LEN - 4
    if ticks > max_ticks:
        print(f"capping ticks {ticks} -> {max_ticks} (seq_len budget)")
        ticks = max_ticks

    import jax

    jax.config.update("jax_platforms", _platform)
    import numpy as np

    from helpers import byte_vocab_tokenizer, tiny_header_params, \
        write_tiny_model
    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.runtime.serving import BatchedGenerator, Request

    d = tempfile.mkdtemp()
    m, t = os.path.join(d, "m.m"), os.path.join(d, "t.t")
    rng = np.random.default_rng(5)
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=SEQ_LEN),
                     rng)
    tfile.write_tfile(t, byte_vocab_tokenizer())

    eng = InferenceEngine(m, t, temperature=0.8, topp=0.9, seed=11)
    gen = BatchedGenerator(eng, n_slots=n_slots)

    for i in range(n_slots):
        r = Request(rid=i, prompt_ids=list(range(2, 2 + PROMPT_LEN)),
                    max_tokens=10 ** 6, temperature=0.8, topp=0.9,
                    seed=100 + i)
        gen.admit(r, i)

    gen.step()  # compile + first ragged dispatch
    t0 = time.perf_counter()
    for _ in range(ticks):
        gen.step()
    dt = time.perf_counter() - t0
    gen_ms = 1e3 * dt / ticks

    # raw program: the same ragged sampled step the generator dispatches,
    # without the scheduler around it
    import jax.numpy as jnp

    from dllama_tpu.models.llama import sampled_step

    kv = gen.kv
    tok = jnp.ones((n_slots,), jnp.int32)
    pos = jnp.asarray(np.full((n_slots,), 40, np.int32))
    temps = jnp.full((n_slots,), 0.8, jnp.float32)
    topps = jnp.full((n_slots,), 0.9, jnp.float32)
    coins = jnp.full((n_slots,), 0.5, jnp.float32)
    step = jax.jit(sampled_step, static_argnums=1)
    tokn, kv = step(eng.params, gen.cfg, tok[:, None], pos, kv, temps,
                    topps, coins)
    jax.block_until_ready(tokn)
    t0 = time.perf_counter()
    for i in range(ticks):
        tokn, kv = step(eng.params, gen.cfg, tok[:, None], pos, kv, temps,
                        topps, coins)
    jax.block_until_ready(tokn)
    raw_ms = 1e3 * (time.perf_counter() - t0) / ticks

    print(f"slots={n_slots} ticks={ticks}")
    print(f"generator.step(): {gen_ms:.2f} ms/tick "
          f"({n_slots * 1e3 / gen_ms:.0f} tok/s aggregate)")
    print(f"raw ragged dispatch: {raw_ms:.2f} ms/tick")
    print(f"host overhead: {gen_ms - raw_ms:.2f} ms/tick "
          f"({100 * (gen_ms - raw_ms) / gen_ms:.0f}% of tick)")


if __name__ == "__main__":
    main()
