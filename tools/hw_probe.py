"""On-chip microbenchmarks that validate bench.py's methodology.

Round-3's verdict flagged `hbm_util_decode = 5.5` — a measured decode rate
5.5x above the HBM roofline computed from the chip's nameplate specs
(bench.py detect_specs), which is physically impossible if every Q40 byte
streams from HBM each step.  This probe separates the two possible causes:

* the device behind the axon tunnel is faster than its "TPU v5 lite" label
  (fix: detect_specs constants), or
* the timing methodology (async dispatch chain + one block_until_ready)
  under-counts (fix: bench.py measurement).

Stages (each prints one JSON line; run standalone on the real chip):

  mem        device memory_stats — real HBM capacity
  dispatch   round-trip latency of a trivial jitted program (tunnel floor)
  hbm_bw     effective GB/s of a reduction over a 2 GiB int8 array,
             measured BOTH as an async chain and with per-rep blocking
  mxu        bf16 matmul TFLOP/s (4096^3, 8192-batched)
  decode     1b-preset greedy decode: async-chain timing (bench.py's way)
             vs per-step block_until_ready timing vs wall time for 2x steps
             (doubling test: real serial execution must double)
  chunked    per-dispatch wall time of greedy_steps K=32, timed one
             dispatch at a time (bench saw a model-size-independent
             ~1.1 s/dispatch — fixed overhead, not compute)

FINDING (first run on the real chip, 2026-07-31): ``block_until_ready`` on
the axon tunnel does NOT wait for device execution — it returned 2 GiB
reductions in 20 us ("86 TB/s"), 4096^3 matmuls at "9.7 PFLOP/s", and an
8B-shape decode FASTER than the 1B shape, while the first dispatch after a
burst absorbed a 2.17 s backlog drain.  Every stage therefore times through
``jax.device_get`` of a value that DEPENDS on the computation: the runtime
cannot hand back real bytes without executing the chain, so a small fetch
(4 B token, scalar sum) is the only trustworthy synchronization point.
bench.py uses the same fetch-based timing for the same reason.
"""

from __future__ import annotations

import json
import os
import sys
import time


_OUT_FILE = [None]  # --out FILE: tee every stage line (JSONL) for the
# roofline observatory's measured ceilings (runtime/roofline reads the
# hbm_bw/mxu stages via DLLAMA_HW_PROBE_FILE or HW_PROBE.json)


def emit(stage: str, **kw) -> None:
    line = json.dumps({"stage": stage, **kw})
    print(line, flush=True)
    if _OUT_FILE[0]:
        with open(_OUT_FILE[0], "a", encoding="utf-8") as f:
            f.write(line + "\n")


def main() -> None:
    argv = list(sys.argv[1:])
    if "--out" in argv:
        i = argv.index("--out")
        try:
            _OUT_FILE[0] = argv[i + 1]
        except IndexError:
            raise SystemExit("--out needs a file path") from None
        del argv[i:i + 2]
        # truncate up front: stale hbm_bw/mxu lines from a PREVIOUS probe
        # (possibly different silicon) must never survive into what the
        # roofline observatory serves as THIS chip's measured ceilings
        open(_OUT_FILE[0], "w").close()
    stages = set(argv) or {
        "mem", "dispatch", "hbm_bw", "mxu", "decode", "chunked"}
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    emit("device", platform=dev.platform, kind=dev.device_kind)

    if "mem" in stages:
        ms = dev.memory_stats() or {}
        emit("mem", **{k: v for k, v in ms.items()
                       if "bytes" in k or "limit" in k})

    if "dispatch" in stages:
        one = jnp.ones((8, 128), jnp.float32)
        f = jax.jit(lambda x: x.sum())
        jax.device_get(f(one))
        lat = []
        for _ in range(20):
            t0 = time.perf_counter()
            jax.device_get(f(one))
            lat.append(time.perf_counter() - t0)
        lat.sort()
        emit("dispatch", p50_ms=round(1e3 * lat[10], 3),
             min_ms=round(1e3 * lat[0], 3), max_ms=round(1e3 * lat[-1], 3))

    if "hbm_bw" in stages:
        n = 2 << 30  # 2 GiB of int8
        big = jax.block_until_ready(
            jax.jit(lambda k: jax.random.bits(k, (n,), jnp.uint8))(
                jax.random.PRNGKey(0)))
        red = jax.jit(lambda x, s: (x.astype(jnp.int32).sum() + s))
        s = jnp.int32(0)
        jax.device_get(red(big, s))  # compile + drain queue
        reps = 8
        t0 = time.perf_counter()
        for _ in range(reps):
            s = red(big, s)
        jax.device_get(s)  # forces the whole chain to have executed
        dt_chain = time.perf_counter() - t0
        per_sync = []
        for _ in range(reps):
            t0 = time.perf_counter()
            s = red(big, s)
            jax.device_get(s)
            per_sync.append(time.perf_counter() - t0)
        emit("hbm_bw", gib=2,
             chain_gbps=round(reps * n / dt_chain / 1e9, 1),
             sync_gbps=round(n / min(per_sync) / 1e9, 1),
             chain_ms_per_rep=round(1e3 * dt_chain / reps, 2),
             sync_ms_min=round(1e3 * min(per_sync), 2),
             sync_ms_max=round(1e3 * max(per_sync), 2))

    if "mxu" in stages:
        m = 4096
        a = jnp.ones((2 * m, m), jnp.bfloat16)
        b = jnp.ones((m, m), jnp.bfloat16)
        mm = jax.jit(lambda a, b: (a @ b))
        tot = jax.jit(lambda x: x.astype(jnp.float32).sum())
        jax.device_get(tot(mm(a, b)))  # compile + drain
        # chained reps (out feeds in) so the final fetch forces every matmul;
        # the 1/m rescale keeps ones-matrices at 1.0 (b is a runtime input,
        # XLA cannot fold the product away)
        mm2 = jax.jit(lambda x, b: (x @ b) * jnp.bfloat16(1.0 / m))
        jax.device_get(tot(mm2(a, b)))
        reps = 16
        t0 = time.perf_counter()
        out = a
        for _ in range(reps):
            out = mm2(out, b)
        jax.device_get(tot(out))  # depends on every rep in the chain
        dt = time.perf_counter() - t0
        emit("mxu", tflops=round(reps * 2 * (2 * m) * m * m / dt / 1e12, 1))

    if "decode" in stages or "chunked" in stages:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        import bench as benchmod

        cfg = benchmod.model_cfg("1b")
        from dllama_tpu.models.llama import greedy_step, greedy_steps
        from dllama_tpu.runtime import KVCache

        params = benchmod.device_random_params(cfg)
        jax.block_until_ready(params)
        kv = KVCache.create(cfg, batch_size=1, dtype=jnp.bfloat16)
        greedy = jax.jit(greedy_step, static_argnums=1, donate_argnums=(4,))
        token = jnp.ones((1,), jnp.int32)
        token, kv = greedy(params, cfg, token[:, None], jnp.int32(0), kv)
        jax.device_get(token)
        pos = 1

        if "decode" in stages:
            for steps in (32, 64):  # doubling test
                t0 = time.perf_counter()
                for i in range(steps):
                    token, kv = greedy(params, cfg, token[:, None],
                                       jnp.int32(pos + i), kv)
                jax.device_get(token)  # 4 B fetch forces the chain
                dt = time.perf_counter() - t0
                emit("decode_chain", steps=steps,
                     ms_per_step=round(1e3 * dt / steps, 3),
                     tok_per_s=round(steps / dt, 1))
                pos += steps
            sync = []
            for i in range(32):
                t0 = time.perf_counter()
                token, kv = greedy(params, cfg, token[:, None],
                                   jnp.int32(pos + i), kv)
                jax.device_get(token)
                sync.append(time.perf_counter() - t0)
            pos += 32
            sync.sort()
            emit("decode_sync", ms_p50=round(1e3 * sync[16], 3),
                 ms_min=round(1e3 * sync[0], 3),
                 ms_max=round(1e3 * sync[-1], 3))

        if "chunked" in stages:
            gsteps = jax.jit(greedy_steps, static_argnums=(1, 5),
                             donate_argnums=(4,))
            K = 32
            t0 = time.perf_counter()
            toks, kv = gsteps(params, cfg, token, jnp.int32(pos), kv, K)
            jax.device_get(toks)
            emit("chunked_compile", s=round(time.perf_counter() - t0, 2))
            pos += K
            for r in range(4):
                t0 = time.perf_counter()
                toks, kv = gsteps(params, cfg, toks[:, -1],
                                  jnp.int32(pos), kv, K)
                jax.device_get(toks)
                dt = time.perf_counter() - t0
                emit("chunked_dispatch", r=r, ms=round(1e3 * dt, 1),
                     ms_per_tok=round(1e3 * dt / K, 2))
                pos += K


if __name__ == "__main__":
    main()
