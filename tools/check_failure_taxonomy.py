#!/usr/bin/env python
"""Failure-taxonomy lint: the finish_reason / resume-outcome /
kvwire-fallback vocabularies are closed-world — declared tuples,
emitting call sites, telemetry label docs, and PERF.md's "Failure
taxonomy" section agree in both directions.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself
lives on the shared dlint framework as the ``failure-taxonomy`` rule —
``python -m tools.dlint --only failure-taxonomy`` is the canonical
entry point; this script exists so direct CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["failure-taxonomy"])


if __name__ == "__main__":
    sys.exit(main())
