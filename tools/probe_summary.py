#!/usr/bin/env python
"""Summarize the chip watcher's probe log (bench_results/probe_log.jsonl)
into the one-paragraph evidence the round changelog needs when the chip
never answered: probe cadence, window covered, healthy count."""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "bench_results", "probe_log.jsonl")
    probes = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    probes.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        print(f"no probe log at {path}")
        return
    if not probes:
        print("probe log empty")
        return
    healthy = [p for p in probes if p.get("healthy")]
    print(f"probes: {len(probes)} from {probes[0]['ts']} to "
          f"{probes[-1]['ts']}")
    print(f"healthy: {len(healthy)}"
          + (f" (first {healthy[0]['ts']})" if healthy else
             " — chip wedged for the entire window (every probe's "
             "jax.devices() timed out at 30 s)"))
    if healthy:
        for p in healthy[:5]:
            print(f"  {p['ts']}  latency {p.get('latency_s')}s")


if __name__ == "__main__":
    main()
