#!/usr/bin/env python
"""SLO objective-name lint: every objective in slo.OBJECTIVES is
grammar-clean, documented (cli grammar, PERF.md, README.md, bench), and
closed-world vs objective-shaped tokens anywhere in the tree.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself
lives on the shared dlint framework as the ``slo-names`` rule —
``python -m tools.dlint --only slo-names`` is the canonical entry point;
this script exists so direct CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["slo-names"])


if __name__ == "__main__":
    sys.exit(main())
