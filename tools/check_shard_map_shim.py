#!/usr/bin/env python
"""shard_map shim lint: every manual-SPMD entry point goes through the parallel.api.shard_map version-compat shim.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself now
lives on the shared dlint framework as the ``shard-map-shim`` rule —
``python -m tools.dlint --only shard-map-shim`` is the canonical entry point;
this script exists so historical CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["shard-map-shim"])


if __name__ == "__main__":
    sys.exit(main())
