#!/usr/bin/env python
"""shard_map shim lint (Makefile ``lint`` target).

Every manual-SPMD entry point must go through the version-compat shim
``dllama_tpu.parallel.api.shard_map``: the top-level ``jax.shard_map``
does not exist on 0.4.x jax and ``jax.experimental.shard_map`` is gone on
>= 0.5, so a raw call site can never trace on one of the two — it only
"works" until the interpreter meets the other jax (the root cause of the
13 seed qcollectives failures; CHANGES.md PR2 bonus (b)). This lint keeps
that world closed: any ``jax.shard_map`` / ``jax.experimental.shard_map``
reference OUTSIDE ``parallel/api.py`` (package, tests, tools) fails.

Pure text scan — no jax import, runnable anywhere ``make lint`` runs.
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# the one module allowed to spell the raw names (it IS the shim)
ALLOWED = {REPO / "dllama_tpu" / "parallel" / "api.py"}

# raw-call spellings: attribute access on jax / jax.experimental, or an
# import from the experimental module. `hasattr(jax, "shard_map")` — the
# shim's own version probe — only appears in the allowed file.
RAW_RE = re.compile(
    r"(jax\.shard_map"
    r"|jax\.experimental\.shard_map"
    r"|from\s+jax\.experimental\.shard_map\s+import"
    r"|from\s+jax\.experimental\s+import\s+shard_map)")

SCAN_DIRS = ("dllama_tpu", "tests", "tools")


_QUOTES = ('"""', "'''")


def _code_lines(text: str):
    """(lineno, line) pairs with ``#`` comments stripped and docstring
    bodies skipped (prose may legitimately NAME the raw spellings — only
    executable references are violations). Crude triple-quote tracking is
    enough for this repo's style: a line with an odd number of the same
    triple-quote toggles string state."""
    in_str: str | None = None
    for lineno, line in enumerate(text.splitlines(), 1):
        if in_str is not None:
            if line.count(in_str) % 2 == 1:
                in_str = None
            continue
        opened = [q for q in _QUOTES if line.count(q) % 2 == 1]
        if opened:
            # code before the opening quote still counts (rare)
            yield lineno, line.split(opened[0], 1)[0]
            in_str = opened[0]
            continue
        yield lineno, line.split("#", 1)[0]


def main() -> int:
    errors: list[str] = []
    n_files = 0
    for d in SCAN_DIRS:
        for py in sorted((REPO / d).rglob("*.py")):
            if py in ALLOWED or py.name == pathlib.Path(__file__).name:
                continue
            n_files += 1
            for lineno, line in _code_lines(py.read_text(encoding="utf-8")):
                m = RAW_RE.search(line)
                if m is None:
                    continue
                errors.append(
                    f"{py.relative_to(REPO)}:{lineno}: raw "
                    f"{m.group(0)!r} — route manual SPMD through "
                    f"dllama_tpu.parallel.api.shard_map (the version-"
                    f"compat shim); a raw call cannot trace on every "
                    f"supported jax")
    if errors:
        for e in errors:
            print(f"❌ {e}", file=sys.stderr)
        return 1
    print(f"✅ {n_files} files: every shard_map call site goes through "
          f"parallel.api's version-compat shim")
    return 0


if __name__ == "__main__":
    sys.exit(main())
