"""Profile N decode steps on the real chip and print top device ops.

Answers "where do the milliseconds go" for the single-step decode program —
the gap between measured decode (14.3 ms/step on the 1b preset, hw_probe)
and its HBM roofline (~1.7 ms).  Usage:

    python tools/profile_decode.py [1b|8b] [n_steps]

Aggregates per-op device time from the xplane capture via the same
no-tensorflow-import proto loader the Eval/Sync split uses
(runtime/profiling._load_xplane).
"""

from __future__ import annotations

import collections
import glob
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    preset = sys.argv[1] if len(sys.argv) > 1 else "1b"
    n_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 4

    import jax
    import jax.numpy as jnp

    import bench as benchmod

    benchmod.force_platform_from_env()  # e.g. cpu self-test
    from dllama_tpu.models.llama import greedy_step
    from dllama_tpu.runtime import KVCache
    from dllama_tpu.runtime.profiling import _device_lines, _load_xplane

    cfg = benchmod.model_cfg(preset)
    params = benchmod.device_random_params(cfg)
    kv = KVCache.create(cfg, batch_size=1, dtype=jnp.bfloat16)
    greedy = jax.jit(greedy_step, static_argnums=1, donate_argnums=(4,))
    token = jnp.ones((1,), jnp.int32)
    token, kv = greedy(params, cfg, token[:, None], jnp.int32(0), kv)
    jax.device_get(token)  # compile + force execution (block_until_ready lies)
    pos = 1
    for i in range(4):  # warm steady state
        token, kv = greedy(params, cfg, token[:, None], jnp.int32(pos + i), kv)
    jax.device_get(token)
    pos += 4

    d = tempfile.mkdtemp(prefix="dllama-prof-")
    t0 = time.perf_counter()
    with jax.profiler.trace(d):
        for i in range(n_steps):
            token, kv = greedy(params, cfg, token[:, None],
                               jnp.int32(pos + i), kv)
        jax.device_get(token)
    wall = time.perf_counter() - t0
    print(f"wall for {n_steps} traced steps: {1e3 * wall:.1f} ms "
          f"({1e3 * wall / n_steps:.2f} ms/step incl. one fetch)")

    paths = glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        print("no xplane capture produced")
        return
    xs = _load_xplane(max(paths, key=os.path.getmtime))

    from dllama_tpu.runtime.profiling import union_span as union_ns

    # Per-lane sum vs interval-UNION: the round-4 open question is a ~1.7x
    # systematic between summed per-op times and measured chain time. A
    # union can't double-count — so if sum >> union the mechanism is
    # overlapping/nested event rows (e.g. module rollups over op rows, or
    # multiple lanes of one core), and the union is the honest device-busy
    # attribution; if union itself exceeds chain time, the chain-side
    # measurement is the suspect instead.
    lanes = []          # (plane_name, line_name, sum_ns, union_ns, n_events)
    all_iv = []
    per_op = collections.Counter()
    per_op_n = collections.Counter()
    best = None         # lane with the largest union = primary attribution
    for plane, line in _device_lines(xs):
        names = {e.id: e.name for e in plane.event_metadata.values()} \
            if hasattr(plane.event_metadata, "values") else {}
        iv, s_ns, n = [], 0, 0
        ops = collections.Counter()
        ops_n = collections.Counter()
        # XEvent.offset_ps is relative to ITS line's timestamp_ns: rebase to
        # absolute ns so the cross-lane union compares real wall intervals
        base_ns = getattr(line, "timestamp_ns", 0) or 0
        for ev in line.events:
            name = names.get(ev.metadata_id, str(ev.metadata_id))
            dur = ev.duration_ps // 1000  # -> ns
            start = base_ns + ev.offset_ps // 1000
            iv.append((start, start + dur))
            ops[name] += dur
            ops_n[name] += 1
            s_ns += dur
            n += 1
        u = union_ns(iv)
        lanes.append((plane.name, line.name, s_ns, u, n))
        all_iv.extend(iv)
        if best is None or u > best[0]:
            best = (u, ops, ops_n, s_ns)
    g_union = union_ns(all_iv)
    print(f"lanes ({len(lanes)}):")
    for pname, lname, s_ns, u, n in lanes:
        print(f"  {pname[-40:]:>40s} / {lname[:20]:<20s} "
              f"sum {s_ns / 1e6:8.2f} ms  union {u / 1e6:8.2f} ms  x{n}")
    sum_all = sum(s for _, _, s, _, _ in lanes)
    print(f"RECONCILE: sum-of-ops {sum_all / 1e6:.2f} ms vs device-busy "
          f"union {g_union / 1e6:.2f} ms over {n_steps} steps "
          f"(sum/union {sum_all / max(g_union, 1):.2f}x; "
          f"union {g_union / 1e6 / n_steps:.3f} ms/step vs wall "
          f"{1e3 * wall / n_steps:.3f} ms/step incl. one fetch)")
    if best is None:
        return
    _, per_op, per_op_n, _ = best
    total_ns = sum(per_op.values())
    width = max((len(n) for n, _ in per_op.most_common(25)), default=10)
    for name, ns in per_op.most_common(25):
        print(f"{name:<{width}}  {ns / 1e6:9.3f} ms  x{per_op_n[name]:<5} "
              f"({100.0 * ns / max(total_ns, 1):5.1f}%)")


if __name__ == "__main__":
    main()
