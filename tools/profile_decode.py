"""Profile N decode steps on the real chip and print the per-op/per-class
device-time attribution.

Answers "where do the milliseconds go" for the single-step decode program —
the gap between measured decode (14.3 ms/step on the 1b preset, hw_probe)
and its HBM roofline (~1.7 ms).  Usage:

    python tools/profile_decode.py [1b|8b] [n_steps] [--json]

The decomposition itself is ``runtime/profiling.op_attribution`` (op
classes: dequant / gemv-matmul / attention / collective / sampling /
other) — the same engine ``POST /debug/profile?ops=1`` serves live, so
the offline tool and the serving surface can never disagree.  ``--json``
prints ONE machine-readable JSON line (the attribution dict plus the
wall measurement) so the ROADMAP #2 profile → A/B → promote loop can be
scripted end to end.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    args = [a for a in sys.argv[1:] if a != "--json"]
    as_json = "--json" in sys.argv[1:]
    preset = args[0] if len(args) > 0 else "1b"
    n_steps = int(args[1]) if len(args) > 1 else 4

    import jax
    import jax.numpy as jnp

    import bench as benchmod

    benchmod.force_platform_from_env()  # e.g. cpu self-test
    from dllama_tpu.models.llama import greedy_step
    from dllama_tpu.runtime import KVCache
    from dllama_tpu.runtime.profiling import op_attribution

    cfg = benchmod.model_cfg(preset)
    params = benchmod.device_random_params(cfg)
    kv = KVCache.create(cfg, batch_size=1, dtype=jnp.bfloat16)
    greedy = jax.jit(greedy_step, static_argnums=1, donate_argnums=(4,))
    token = jnp.ones((1,), jnp.int32)
    token, kv = greedy(params, cfg, token[:, None], jnp.int32(0), kv)
    jax.device_get(token)  # compile + force execution (block_until_ready lies)
    pos = 1
    for i in range(4):  # warm steady state
        token, kv = greedy(params, cfg, token[:, None], jnp.int32(pos + i), kv)
    jax.device_get(token)
    pos += 4

    d = tempfile.mkdtemp(prefix="dllama-prof-")
    t0 = time.perf_counter()
    with jax.profiler.trace(d):
        for i in range(n_steps):
            token, kv = greedy(params, cfg, token[:, None],
                               jnp.int32(pos + i), kv)
        jax.device_get(token)
    wall = time.perf_counter() - t0

    try:
        attrib = op_attribution(d, n_steps=n_steps)
    except RuntimeError as e:
        if as_json:
            print(json.dumps({"preset": preset, "n_steps": n_steps,
                              "error": str(e)}))
        else:
            print(f"no usable xplane capture: {e}")
        return
    attrib["preset"] = preset
    attrib["wall_ms_per_step"] = round(1e3 * wall / n_steps, 3)

    if as_json:
        print(json.dumps(attrib))
        return

    print(f"wall for {n_steps} traced steps: {1e3 * wall:.1f} ms "
          f"({attrib['wall_ms_per_step']:.2f} ms/step incl. one fetch)")
    print(f"lanes ({attrib['n_lanes']}):")
    for ln in attrib["lanes"]:
        print(f"  {ln['plane'][-40:]:>40s} / {ln['line'][:20]:<20s} "
              f"sum {ln['sum_ms']:8.2f} ms  union {ln['union_ms']:8.2f} ms  "
              f"x{ln['n_events']}")
    # Per-lane sum vs interval-UNION: summed per-op times double-count
    # overlapping/nested event rows; the union is the honest device-busy
    # attribution. sum/union >> 1 means the per-op percentages overstate
    # absolute time; a union above chain time points at the chain-side
    # measurement instead.
    print(f"RECONCILE: primary-lane sum-of-ops "
          f"{attrib['total_ms_per_step'] * n_steps:.2f} ms "
          f"(sum/own-union {attrib['sum_over_union']:.2f}x) vs all-lane "
          f"device-busy union "
          f"{attrib['device_busy_ms_per_step'] * n_steps:.2f} ms over "
          f"{n_steps} steps "
          f"(union {attrib['device_busy_ms_per_step']:.3f} ms/step vs wall "
          f"{attrib['wall_ms_per_step']:.3f} ms/step incl. one fetch)")
    print("classes (primary lane):")
    for cls, rec in attrib["classes"].items():
        print(f"  {cls:<14s} {rec['ms_per_step']:9.3f} ms/step "
              f"({100.0 * rec['frac']:5.1f}%)")
    width = max((len(o["name"]) for o in attrib["top_ops"]), default=10)
    for o in attrib["top_ops"]:
        print(f"{o['name']:<{width}}  {o['ms_per_step'] * n_steps:9.3f} ms  "
              f"x{o['count']:<5} ({100.0 * o['frac']:5.1f}%)  [{o['class']}]")


if __name__ == "__main__":
    main()
