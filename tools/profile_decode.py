"""Profile N decode steps on the real chip and print top device ops.

Answers "where do the milliseconds go" for the single-step decode program —
the gap between measured decode (14.3 ms/step on the 1b preset, hw_probe)
and its HBM roofline (~1.7 ms).  Usage:

    python tools/profile_decode.py [1b|8b] [n_steps]

Aggregates per-op device time from the xplane capture via the same
no-tensorflow-import proto loader the Eval/Sync split uses
(runtime/profiling._load_xplane).
"""

from __future__ import annotations

import collections
import glob
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    preset = sys.argv[1] if len(sys.argv) > 1 else "1b"
    n_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 4

    import jax
    import jax.numpy as jnp

    import bench as benchmod
    from dllama_tpu.models.llama import greedy_step
    from dllama_tpu.runtime import KVCache
    from dllama_tpu.runtime.profiling import _device_lines, _load_xplane

    cfg = benchmod.model_cfg(preset)
    params = benchmod.device_random_params(cfg)
    kv = KVCache.create(cfg, batch_size=1, dtype=jnp.bfloat16)
    greedy = jax.jit(greedy_step, static_argnums=1, donate_argnums=(4,))
    token = jnp.ones((1,), jnp.int32)
    token, kv = greedy(params, cfg, token[:, None], jnp.int32(0), kv)
    jax.device_get(token)  # compile + force execution (block_until_ready lies)
    pos = 1
    for i in range(4):  # warm steady state
        token, kv = greedy(params, cfg, token[:, None], jnp.int32(pos + i), kv)
    jax.device_get(token)
    pos += 4

    d = tempfile.mkdtemp(prefix="dllama-prof-")
    t0 = time.perf_counter()
    with jax.profiler.trace(d):
        for i in range(n_steps):
            token, kv = greedy(params, cfg, token[:, None],
                               jnp.int32(pos + i), kv)
        jax.device_get(token)
    wall = time.perf_counter() - t0
    print(f"wall for {n_steps} traced steps: {1e3 * wall:.1f} ms "
          f"({1e3 * wall / n_steps:.2f} ms/step incl. one fetch)")

    paths = glob.glob(os.path.join(d, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        print("no xplane capture produced")
        return
    xs = _load_xplane(max(paths, key=os.path.getmtime))

    per_op = collections.Counter()
    per_op_n = collections.Counter()
    total_ns = 0
    lanes = 0
    for plane, line in _device_lines(xs):
        lanes += 1
        names = {e.id: e.name for e in plane.event_metadata.values()} \
            if hasattr(plane.event_metadata, "values") else {}
        for ev in line.events:
            name = names.get(ev.metadata_id, str(ev.metadata_id))
            per_op[name] += ev.duration_ps // 1000  # -> ns
            per_op_n[name] += 1
            total_ns += ev.duration_ps // 1000
    print(f"device lanes: {lanes}; total device time "
          f"{total_ns / 1e6:.1f} ms over {n_steps} steps "
          f"({total_ns / 1e6 / n_steps:.2f} ms/step)")
    width = max((len(n) for n, _ in per_op.most_common(25)), default=10)
    for name, ns in per_op.most_common(25):
        print(f"{name:<{width}}  {ns / 1e6:9.3f} ms  x{per_op_n[name]:<5} "
              f"({100.0 * ns / max(total_ns, 1):5.1f}%)")


if __name__ == "__main__":
    main()
