#!/usr/bin/env python
"""Perf-regression sentinel: record a noise-aware baseline from bench.py
output and check later runs against it.

The BENCH trajectory had no enforced floor — a PR could silently give back
the optimization ledger's wins and nothing would go red until a human
re-read the numbers. This tool closes that loop:

    python tools/perf_baseline.py record BENCH.json --name r05
    python tools/perf_baseline.py check  BENCH.json

``record`` writes ``PERF_BASELINE.json`` (repo root; ``--baseline-file``
overrides): per-metric value + a noise threshold. ``check`` compares a
bench result against it and exits 1 naming every regressed metric.
``bench.py --baseline {check,update}`` wraps the same functions around a
live bench run (``make perf-check``).

Noise model (RTT-floor-aware — PERF.md "Methodology" rule 2): every bench
region is fetch-forced and pays one host↔device round-trip (~67 ms on the
axon tunnel), so a decode region of N steps cannot resolve a change
smaller than ``rtt / (N × ms_per_step)`` of itself. The per-metric
threshold is ``max(10%, that floor)`` — on the 1b preset (5.5 ms steps)
the RTT floor (~19%) dominates; on the 8b preset (29 ms steps) the flat
10% does. A difference inside the threshold is noise, not a verdict.

Skip semantics are first-class: a side that never measured (backend down
→ ``skipped: true``; a stage that errored; a metric absent from the
current run) is **no evidence** — reported as such, never a pass and
never a fail. A check where nothing overlaps exits 0 with an explicit
``no_evidence`` verdict, so CI stays green on hardware-less runners
without pretending it verified anything.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "PERF_BASELINE.json")

# higher-is-better rates and lower-is-better latencies the sentinel guards
# (stage-scoped: the key is "<stage>.<field>")
RATE_FIELDS = ("decode_tok_per_s", "prefill_tok_per_s",
               "sampled_decode_tok_per_s", "chunked_decode_tok_per_s",
               "paged_decode_tok_per_s", "agg_tok_per_s",
               "accepted_tok_per_s", "decode_tok_per_s_q80",
               "sessions_per_chip", "slo_compliance_min",
               "eval_tok_per_s", "jain_index")
LATENCY_FIELDS = ("decode_ms_per_step", "verify_k4_ms",
                  "ttft_ms_p50", "ttft_ms_p95", "resume_ttft_p95_ms",
                  "comm_exposed_ms", "slo_worst_burn")
# decode-region fields whose RTT floor scales with the region length
_DECODE_REGION_FIELDS = ("decode_tok_per_s", "decode_ms_per_step",
                         "sampled_decode_tok_per_s",
                         "chunked_decode_tok_per_s")

DEFAULT_NOISE_FRAC = 0.10
MAX_NOISE_FRAC = 0.50  # a region THIS close to the RTT floor is reported
# null by bench.py anyway; cap so a borderline one can't excuse anything
REGION_STEPS = 64      # bench.py's decode_steps default per measured region
REGION_STEPS_BATCHED = 32  # the @b16 stages run half the steps (bench.py
# stage_child's b16 kwargs) — their RTT floor is twice as tall
# A zero-valued lower-is-better baseline (e.g. fully-overlapped exposed
# comm) has no relative scale: any value below this absolute band is
# timer/union jitter beneath the honest-timing resolution, not a move.
ZERO_LATENCY_TOL_MS = 0.5


def last_json_line(text: str) -> dict | None:
    """The last parseable JSON-object line in ``text`` (bench emits
    exactly one; logs/wrappers may surround it), or None."""
    for line in str(text).splitlines()[::-1]:
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                return obj
    return None


def load_bench_json(path: str) -> dict:
    """A bench result from any of its on-disk shapes: the one-line emit,
    a capture's BENCH_live.json, or the driver's BENCH_rN.json wrapper
    ({n, cmd, rc, tail, parsed})."""
    if os.path.isdir(path):
        path = os.path.join(path, "BENCH_live.json")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        whole = json.loads(text)
        if isinstance(whole, dict):
            if "stages" in whole or "value" in whole:
                return whole
            if isinstance(whole.get("parsed"), dict):
                return whole["parsed"]
            if "tail" in whole:
                found = last_json_line(whole["tail"])
                if found is not None:
                    return found
    except json.JSONDecodeError:
        pass
    found = last_json_line(text)
    if found is not None:
        return found
    raise ValueError(f"no bench JSON found in {path}")


def write_baseline(doc: dict, path: str) -> None:
    """THE baseline writer — `tools/perf_baseline.py record` and
    `bench.py --baseline update` both come through here, so the two can
    never drift in formatting (a byte-stable committed file diffs
    cleanly across either writer)."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"✅ baseline '{doc['name']}' → {path} "
          f"({len(doc['metrics'])} metrics)")


def _noise_frac(stage: dict, field: str, stage_name: str = "") -> float:
    """Per-metric threshold: the flat noise floor, raised to the RTT
    floor's share of the measured region when that is larger."""
    frac = DEFAULT_NOISE_FRAC
    rtt = stage.get("fetch_rtt_ms")
    ms_step = stage.get("decode_ms_per_step")
    if rtt and ms_step and field in _DECODE_REGION_FIELDS:
        steps = (REGION_STEPS_BATCHED if stage_name.endswith("@b16")
                 else REGION_STEPS)
        region_ms = ms_step * steps
        if region_ms > 0:
            frac = max(frac, min(MAX_NOISE_FRAC, rtt / region_ms))
    return round(frac, 4)


def extract_metrics(bench: dict) -> dict:
    """Flatten a bench result into the sentinel's comparable metrics:
    ``{"<stage>.<field>": {value, higher_better, noise_frac}}`` plus the
    headline roofline fraction when present. Skipped results and errored
    stages contribute NOTHING (no evidence is not a zero)."""
    out: dict = {}
    if bench.get("skipped"):
        return out
    for stage, rec in (bench.get("stages") or {}).items():
        if not isinstance(rec, dict) or rec.get("skipped") \
                or rec.get("error"):
            continue
        # `is not None`, not truthiness: a measured 0.0 (e.g. a fully
        # overlapped comm_exposed_ms) is evidence — dropping it would let
        # a later 0 → 50 ms regression pass unnamed. bench.py reports an
        # unmeasured region as null, which IS excluded here.
        for field in RATE_FIELDS:
            v = rec.get(field)
            if v is not None:
                out[f"{stage}.{field}"] = {
                    "value": float(v), "higher_better": True,
                    "noise_frac": _noise_frac(rec, field, stage)}
        for field in LATENCY_FIELDS:
            v = rec.get(field)
            if v is not None:
                out[f"{stage}.{field}"] = {
                    "value": float(v), "higher_better": False,
                    "noise_frac": _noise_frac(rec, field, stage)}
    roof = bench.get("roofline") or {}
    if roof.get("roofline_fraction") is not None:
        out["headline.roofline_fraction"] = {
            "value": float(roof["roofline_fraction"]),
            "higher_better": True, "noise_frac": DEFAULT_NOISE_FRAC}
    # per program-family fractions (decode vs prefill vs paged): lock each
    # family's distance-to-ceiling in independently, so a paged-path
    # regression can't hide behind a steady headline decode number (a
    # family with no_evidence contributes nothing, same as a stage)
    for fam, rec in (roof.get("families") or {}).items():
        frac = (rec or {}).get("roofline_fraction")
        if frac is not None:
            out[f"family.{fam}.roofline_fraction"] = {
                "value": float(frac), "higher_better": True,
                "noise_frac": DEFAULT_NOISE_FRAC}
    return out


def make_baseline(bench: dict, name: str, source: str = "") -> dict:
    metrics = extract_metrics(bench)
    if not metrics:
        raise ValueError(
            "bench result carries no measured metrics to baseline "
            + ("(skipped: " + str(bench.get("skip_reason")) + ")"
               if bench.get("skipped") else "(every stage errored?)"))
    return {
        "name": name,
        "recorded_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "source": source,
        "git": bench.get("git"),
        "device_kind": bench.get("device_kind"),
        "bench_metric": bench.get("metric"),
        "metrics": metrics,
    }


def compare(bench: dict, baseline: dict) -> dict:
    """One check: every baseline metric against the current result.

    Verdict grammar — ``regressions`` (worse beyond the threshold),
    ``improvements`` (better beyond it), ``within_noise``, and
    ``no_evidence`` (the current side did not measure that metric: a
    skipped run, an errored stage, different hardware tier). Only
    ``regressions`` can fail a check; ``no_evidence`` never passes or
    fails it."""
    current = extract_metrics(bench)
    out: dict = {"baseline_name": baseline.get("name"),
                 "regressions": [], "improvements": [],
                 "within_noise": [], "no_evidence": []}
    if bench.get("skipped"):
        out["skipped"] = True
        out["skip_reason"] = bench.get("skip_reason")
    for key, base in sorted((baseline.get("metrics") or {}).items()):
        cur = current.get(key)
        if cur is None:
            out["no_evidence"].append({
                "metric": key, "baseline": base["value"],
                "reason": ("run skipped (no hardware)" if bench.get("skipped")
                           else "metric not measured in this run")})
            continue
        bv, cv = base["value"], cur["value"]
        thresh = max(base.get("noise_frac", DEFAULT_NOISE_FRAC),
                     cur.get("noise_frac", DEFAULT_NOISE_FRAC))
        if bv == 0:
            # a zero baseline (e.g. fully-overlapped exposed comm) has no
            # relative scale: staying zero is a perfect hold, sub-resolution
            # jitter on a latency metric is NOISE (a 0.4 µs union sliver
            # must not hard-fail CI as a "-100% regression"), and anything
            # past the band is a full-size move in the metric's direction
            if cv == 0:
                delta = 0.0
            elif base.get("higher_better", True):
                delta = 1.0  # grew from zero: improvement-positive
            elif cv <= ZERO_LATENCY_TOL_MS:
                delta = 0.0
            else:
                delta = -1.0
        elif base.get("higher_better", True):
            delta = (cv - bv) / bv
        else:
            delta = (bv - cv) / bv  # improvement-positive either way
        # the absolute sub-resolution band applies to EVERY latency
        # metric, not only exact-zero baselines: 0.15 ms → 0.35 ms of
        # union sliver is the same timer jitter as 0 → 0.2
        if not base.get("higher_better", True) \
                and abs(cv - bv) <= ZERO_LATENCY_TOL_MS:
            delta = 0.0
        rec = {"metric": key, "baseline": bv, "current": cv,
               "delta_frac": round(delta, 4), "threshold_frac": thresh}
        if delta < -thresh:
            out["regressions"].append(rec)
        elif delta > thresh:
            out["improvements"].append(rec)
        else:
            out["within_noise"].append(rec)
    out["verdict"] = ("regression" if out["regressions"]
                      else "no_evidence" if not (out["within_noise"]
                                                 or out["improvements"])
                      else "ok")
    return out


def format_report(cmp: dict) -> str:
    lines = [f"perf-baseline check vs '{cmp.get('baseline_name')}': "
             f"{cmp['verdict'].upper()}"]
    for r in cmp["regressions"]:
        lines.append(f"  ❌ REGRESSED {r['metric']}: {r['baseline']} -> "
                     f"{r['current']} ({100 * r['delta_frac']:+.1f}%, "
                     f"threshold ±{100 * r['threshold_frac']:.0f}%)")
    for r in cmp["improvements"]:
        lines.append(f"  ✅ improved {r['metric']}: {r['baseline']} -> "
                     f"{r['current']} ({100 * r['delta_frac']:+.1f}%)")
    for r in cmp["within_noise"]:
        lines.append(f"  · within noise {r['metric']}: {r['baseline']} -> "
                     f"{r['current']} ({100 * r['delta_frac']:+.1f}% of "
                     f"±{100 * r['threshold_frac']:.0f}%)")
    for r in cmp["no_evidence"]:
        lines.append(f"  ∅ no evidence {r['metric']} "
                     f"(baseline {r['baseline']}): {r['reason']}")
    if cmp["verdict"] == "no_evidence":
        lines.append("  (nothing measured overlaps the baseline — not a "
                     "pass, not a fail; run on hardware for a verdict)")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", choices=("record", "check"))
    ap.add_argument("result", help="bench JSON (one-line emit, capture "
                                   "dir, or BENCH_rN.json wrapper)")
    ap.add_argument("--name", default=None,
                    help="baseline name (record mode; default: result "
                         "file stem)")
    ap.add_argument("--baseline-file", default=DEFAULT_BASELINE)
    args = ap.parse_args()

    try:
        bench = load_bench_json(args.result)
    except (OSError, ValueError) as e:
        # a missing/corrupt RESULT file is a filesystem error, not a perf
        # verdict: named rc 2, never the regression exit code
        print(f"❌ result file unusable: {e}", file=sys.stderr)
        return 2
    if args.mode == "record":
        name = args.name or os.path.splitext(
            os.path.basename(args.result))[0]
        doc = make_baseline(bench, name, source=args.result)
        write_baseline(doc, args.baseline_file)
        return 0

    try:
        with open(args.baseline_file, encoding="utf-8") as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        # unreadable OR corrupt: a named rc-2, never a traceback that a
        # CI gate misreads as a perf regression
        print(f"❌ baseline file unusable: {e}", file=sys.stderr)
        return 2
    cmp = compare(bench, baseline)
    print(format_report(cmp))
    return 1 if cmp["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
