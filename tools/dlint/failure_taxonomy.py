"""Failure-taxonomy rule.

Three closed vocabularies name how requests end when something goes
wrong, one per tier: ``finish_reason`` on every terminal SSE chunk
(``serve/api.py FINISH_REASONS``), the mid-stream failover outcome on
``dllama_router_stream_resumes_total`` (``serve/router.py
RESUME_OUTCOMES``), and the KV-migration fallback reason on
``dllama_kvwire_fallback_total`` (``runtime/kvwire.py
FALLBACK_REASONS``). Each is the same three-way contract slo-names
enforces for objectives: the DECLARED tuple, the CALL SITES that emit
members, and the OPERATOR DOCS (telemetry label help + PERF.md's
"Failure taxonomy" section) must agree in both directions — a literal
outside its tuple is a typo that silently forks the vocabulary, a
declared member nothing emits is dead taxonomy, and an undocumented
member is an alert nobody can interpret.

The vocabularies are AST-extracted, never imported: ``serve/api.py``
pulls the engine (jax) at import time, and dlint must run on bare CI
runners before the native build. Only ``runtime/telemetry`` (jax-free
by design) is imported, for the metric help strings.
"""

from __future__ import annotations

import ast
import sys

from .core import REPO, Finding, Project, rule

# (tuple name, declaring file) — the three declarations
VOCABS = (
    ("FINISH_REASONS", "dllama_tpu/serve/api.py"),
    ("RESUME_OUTCOMES", "dllama_tpu/serve/router.py"),
    ("FALLBACK_REASONS", "dllama_tpu/runtime/kvwire.py"),
)
PERF = "PERF.md"
PERF_SECTION = "Failure taxonomy"


def _tuple_const(sf, name: str) -> tuple | None:
    """The module-level ``NAME = ("a", "b", ...)`` assignment's value,
    extracted from the AST (no import)."""
    if sf is None or sf.tree is None:
        return None
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Name) and tgt.id == name):
            continue
        if isinstance(node.value, ast.Tuple) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in node.value.elts):
            return tuple(e.value for e in node.value.elts)
    return None


def _str_const(node) -> str | None:
    return (node.value if isinstance(node, ast.Constant)
            and isinstance(node.value, str) else None)


def _finish_reason_sites(sf) -> list[tuple[int, str]]:
    """Every ``finish_reason`` literal the api server can emit:
    ``finish_reason = "x"`` assignments, ``finish_reason ==/in ...``
    comparisons, and ``stream_abort("x")`` terminal events."""
    out: list[tuple[int, str]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "finish_reason":
            v = _str_const(node.value)
            if v is not None:
                out.append((node.lineno, v))
        elif isinstance(node, ast.Compare) \
                and isinstance(node.left, ast.Name) \
                and node.left.id == "finish_reason":
            for cmp in node.comparators:
                elts = cmp.elts if isinstance(cmp, ast.Tuple) else [cmp]
                for e in elts:
                    v = _str_const(e)
                    if v is not None:
                        out.append((e.lineno, v))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "stream_abort" and node.args:
            v = _str_const(node.args[0])
            if v is not None:
                out.append((node.lineno, v))
    return out


def _resume_outcome_sites(sf) -> list[tuple[int, str]]:
    """Every resume-outcome literal the router can count: ``outcome =
    "x"`` assignments (the terminal-abort classification) and literal
    ``c_resumes.inc(outcome="x")`` keywords."""
    out: list[tuple[int, str]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "outcome":
            v = _str_const(node.value)
            if v is not None:
                out.append((node.lineno, v))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "inc" \
                and isinstance(node.func.value, ast.Attribute) \
                and node.func.value.attr == "c_resumes":
            for kw in node.keywords:
                if kw.arg == "outcome":
                    v = _str_const(kw.value)
                    if v is not None:
                        out.append((node.lineno, v))
    return out


def _fallback_reason_sites(sf_kvwire, sf_serving) -> list[tuple[str, int, str]]:
    """Every fallback-reason literal: ``classify_failure``'s returns
    (kvwire.py) plus ``reason = "x"`` assignments inside the scheduler's
    ``_service_migrations`` (the import-side ``exhaustion`` case)."""
    out: list[tuple[str, int, str]] = []
    for node in ast.walk(sf_kvwire.tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "classify_failure":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return):
                    v = _str_const(sub.value)
                    if v is not None:
                        out.append((sf_kvwire.rel, sub.lineno, v))
    for node in ast.walk(sf_serving.tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_service_migrations":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) \
                        and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and sub.targets[0].id == "reason":
                    v = _str_const(sub.value)
                    if v is not None:
                        out.append((sf_serving.rel, sub.lineno, v))
    return out


def _metric_help(metric: str) -> str:
    sys.path.insert(0, str(REPO))
    try:
        from dllama_tpu.runtime.telemetry import SPECS
    finally:
        sys.path.pop(0)
    spec = SPECS.get(metric)
    return spec.help if spec is not None else ""


def check(project: Project) -> tuple[list[Finding], str]:
    findings: list[Finding] = []

    def f(path, msg, lineno=0):
        findings.append(Finding("failure-taxonomy", path, lineno, msg))

    vocabs: dict[str, tuple] = {}
    for name, rel in VOCABS:
        sf = project.file(rel)
        vals = _tuple_const(sf, name)
        if vals is None:
            f(rel, f"expected a module-level {name} = (...) tuple of "
                   f"string literals (the declared failure vocabulary)")
            vals = ()
        elif len(set(vals)) != len(vals):
            f(rel, f"{name} has duplicate members: {vals}")
        vocabs[name] = vals

    # forward, docs: every member spelled in PERF.md's taxonomy section
    perf = project.file(PERF)
    perf_text = perf.text if perf is not None else ""
    if PERF_SECTION not in perf_text:
        f(PERF, f"PERF.md needs a {PERF_SECTION!r} section documenting "
                f"the three failure vocabularies")
    for name, rel in VOCABS:
        for member in vocabs[name]:
            if f'"{member}"' not in perf_text \
                    and f"`{member}`" not in perf_text:
                f(PERF, f"{name} member {member!r} ({rel}) is not "
                        f"documented in PERF.md")

    # forward, telemetry: the label-bearing metrics' help strings must
    # name every member (the operator reads the /metrics exposition)
    for name, metric in (("RESUME_OUTCOMES",
                          "dllama_router_stream_resumes_total"),
                         ("FALLBACK_REASONS",
                          "dllama_kvwire_fallback_total")):
        help_text = _metric_help(metric)
        if not help_text:
            f("dllama_tpu/runtime/telemetry.py",
              f"{metric} is not registered in telemetry.SPECS")
            continue
        for member in vocabs[name]:
            if member not in help_text:
                f("dllama_tpu/runtime/telemetry.py",
                  f"{metric} help does not document the {name} "
                  f"member {member!r}")

    # reverse: every emitted literal is declared, every declared member
    # is emitted somewhere (closed world in both directions)
    api = project.file("dllama_tpu/serve/api.py")
    router = project.file("dllama_tpu/serve/router.py")
    kvwire = project.file("dllama_tpu/runtime/kvwire.py")
    serving = project.file("dllama_tpu/runtime/serving.py")
    sites = {
        "FINISH_REASONS": [(api.rel, ln, v)
                           for ln, v in _finish_reason_sites(api)],
        "RESUME_OUTCOMES": [(router.rel, ln, v)
                            for ln, v in _resume_outcome_sites(router)],
        "FALLBACK_REASONS": _fallback_reason_sites(kvwire, serving),
    }
    for name, _ in VOCABS:
        emitted = set()
        for rel, lineno, val in sites[name]:
            emitted.add(val)
            if vocabs[name] and val not in vocabs[name]:
                f(rel, f"literal {val!r} is outside the declared "
                       f"{name} vocabulary {vocabs[name]} (typo, or "
                       f"extend the tuple)", lineno)
        for member in vocabs[name]:
            if member not in emitted:
                f(dict(VOCABS)[name],
                  f"{name} member {member!r} is declared but no call "
                  f"site emits it (dead taxonomy)")

    n = sum(len(v) for v in vocabs.values())
    n_sites = sum(len(s) for s in sites.values())
    return findings, (f"3 failure vocabularies ({n} members, {n_sites} "
                      f"emit sites): declarations, call sites, "
                      f"telemetry label docs, and PERF.md all agree")


rule("failure-taxonomy",
     "finish_reason / resume-outcome / kvwire-fallback vocabularies are "
     "closed-world: declared tuples, emitting call sites, telemetry "
     "label docs, and PERF.md's Failure taxonomy section agree in both "
     "directions")(check)
