"""Failpoint-site rule (migrated from ``tools/check_failpoint_sites.py``).

The chaos suite can only drive failure paths whose injection sites exist
and are named what the docs say. Closed-world both directions: every
``failpoints.fire("<name>")`` call site uses a name documented in the
Site registry of ``runtime/failpoints.py``'s module docstring, and every
documented site fires somewhere.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Project, rule

PKG = "dllama_tpu"
FAILPOINTS = f"{PKG}/runtime/failpoints.py"
_REGISTRY_RE = re.compile(r"^\* ``([a-z_]+)``", re.MULTILINE)


def check(project: Project,
          failpoints_rel: str = FAILPOINTS) -> tuple[list[Finding], str]:
    findings: list[Finding] = []

    fsf = project.file(failpoints_rel)
    if fsf is None or fsf.tree is None:
        findings.append(Finding("failpoint-sites", failpoints_rel, 0,
                                "missing or unparseable"))
        return findings, ""
    doc = ast.get_docstring(fsf.tree) or ""
    documented = set(_REGISTRY_RE.findall(doc))

    fired: dict[str, list[tuple[str, int]]] = {}
    for sf in project.walk(PKG):
        if sf.rel == failpoints_rel or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "fire"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "failpoints"):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                findings.append(Finding(
                    "failpoint-sites", sf.rel, node.lineno,
                    "failpoints.fire() with a non-literal site name — "
                    "the closed world can't see it"))
                continue
            fired.setdefault(node.args[0].value, []).append(
                (sf.rel, node.lineno))

    if not documented:
        findings.append(Finding(
            "failpoint-sites", failpoints_rel, 0,
            "no Site registry entries found in the module docstring "
            "(expected '* ``name`` — ...' lines)"))
    for name, sites in sorted(fired.items()):
        if name not in documented:
            findings.append(Finding(
                "failpoint-sites", sites[0][0], sites[0][1],
                f"site {name!r} is fired here but not documented in the "
                f"failpoints.py Site registry"))
    for name in sorted(documented - set(fired)):
        findings.append(Finding(
            "failpoint-sites", failpoints_rel, 0,
            f"site {name!r} is documented in the Site registry but "
            f"never fired anywhere in {PKG}/ — dead chaos surface"))

    n_sites = sum(len(v) for v in fired.values())
    return findings, (f"failpoint sites closed-world: {len(fired)} names "
                      f"over {n_sites} call sites, all documented (and "
                      f"vice versa)")


rule("failpoint-sites",
     "every failpoints.fire() site is documented in the registry and "
     "every documented site fires")(check)
