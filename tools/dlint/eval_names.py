"""Eval config-name rule.

The quality observatory's config vocabulary
(``dllama_tpu.runtime.telemetry.EVAL_CONFIGS``) names the same thing in
five places: the eval CLI's ``--compare`` grammar, the ``config`` label
on the ``dllama_eval_*`` metric family, the parity map inside the
committed ``QUALITY_BASELINE.json``, the bench eval scenario's
per-config section, and the README docs. This rule keeps the vocabulary
closed in BOTH directions: every declared config is grammar-clean,
derived (not hand-copied) into the CLI grammar, recorded in the
committed baseline, and documented — and every config-shaped consumer
(the parity pairs, the baseline's keys) names a declared config. A
typo'd config name must fail lint, not silently never gate. Importing
only the telemetry module keeps this runnable without jax.
"""

from __future__ import annotations

import json
import re
import sys

from .core import REPO, Finding, Project, rule

# the grammar each EVAL_CONFIGS member must satisfy
GRAMMAR_RE = re.compile(r"^[a-z][a-z0-9_]{0,31}$")

T = "dllama_tpu/runtime/telemetry.py"
BASELINE = "QUALITY_BASELINE.json"
# files that must DERIVE the vocabulary from telemetry.EVAL_CONFIGS
# instead of hand-spelling it (a hand-copied list is how grammars drift)
DERIVING_FILES = ("dllama_tpu/serve/cli.py",
                  "dllama_tpu/runtime/evalharness.py",
                  "bench.py", "tools/quality_baseline.py")
# operator-facing docs where every config must be spelled out
DOC_FILES = ("README.md",)


def _load_vocab():
    sys.path.insert(0, str(REPO))
    try:
        from dllama_tpu.runtime.telemetry import (EVAL_CONFIGS, EVAL_PARITY,
                                                  SPECS)
    finally:
        sys.path.pop(0)
    return EVAL_CONFIGS, EVAL_PARITY, SPECS


def check(project: Project, vocab=None) -> tuple[list[Finding], str]:
    """``vocab`` — an ``(EVAL_CONFIGS, EVAL_PARITY, SPECS)`` triple —
    is injectable for fixture self-tests; defaults to the repo's live
    vocabulary."""
    configs, parity, specs = vocab if vocab is not None else _load_vocab()
    findings: list[Finding] = []

    def f(path, msg, lineno=0):
        findings.append(Finding("eval-names", path, lineno, msg))

    for name in configs:
        if not GRAMMAR_RE.match(name):
            f(T, f"eval config {name!r} violates the grammar "
                 f"([a-z][a-z0-9_]*)")

    # the parity contract only ranges over declared configs, and a pair
    # must relate two DIFFERENT configs (a reflexive pair gates nothing)
    for a, b in parity:
        for side in (a, b):
            if side not in configs:
                f(T, f"EVAL_PARITY references {side!r}, which is not in "
                     f"EVAL_CONFIGS")
        if a == b:
            f(T, f"EVAL_PARITY pair ({a!r}, {b!r}) is reflexive")

    # the dllama_eval_* family the configs label must be registered
    for metric in ("dllama_eval_tokens_total", "dllama_eval_nll_total",
                   "dllama_eval_perplexity"):
        if metric not in specs:
            f(T, f"eval metric {metric!r} is not registered in "
                 f"telemetry.SPECS")

    # consumers must derive the vocabulary, not hand-copy it: the token
    # EVAL_CONFIGS (or EVAL_PARITY for the gates) must appear in each
    for rel in DERIVING_FILES:
        sf = project.file(rel)
        text = sf.text if sf is not None else ""
        if "EVAL_CONFIGS" not in text and "EVAL_PARITY" not in text:
            f(rel, "does not reference telemetry.EVAL_CONFIGS/"
                   "EVAL_PARITY — the eval config grammar must be "
                   "derived from the closed vocabulary, not hand-spelled")

    # forward docs: every config spelled in the operator-facing files
    for rel in DOC_FILES:
        sf = project.file(rel)
        text = sf.text if sf is not None else ""
        for name in configs:
            if name not in text:
                f(rel, f"eval config {name!r} is not mentioned in {rel} "
                       f"(grammar/docs drift)")

    # the committed quality baseline's parity keys are the vocabulary's
    # on-disk mirror: both directions — no undeclared key, no missing
    # config (the builtin recorder scores every config)
    sf = project.file(BASELINE)
    if sf is None:
        f(BASELINE, "committed quality baseline is missing (rerun "
                    "`python tools/quality_baseline.py record`)")
    else:
        try:
            doc = json.loads(sf.text)
        except json.JSONDecodeError as e:
            doc = None
            f(BASELINE, f"not JSON: {e}")
        if isinstance(doc, dict):
            for dataset, hexes in sorted((doc.get("parity") or {}).items()):
                for key in hexes:
                    if key not in configs:
                        f(BASELINE, f"parity key {key!r} (dataset "
                                    f"{dataset!r}) is not in "
                                    f"telemetry.EVAL_CONFIGS")
                for name in configs:
                    if name not in hexes:
                        f(BASELINE, f"config {name!r} has no recorded "
                                    f"parity hex for dataset {dataset!r} "
                                    f"(re-record the baseline)")

    return findings, (f"{len(configs)} eval configs: grammar + parity "
                      f"pairs + derived grammars + docs + committed "
                      f"baseline all consistent")


rule("eval-names",
     "every eval config name is grammar-clean, derived from "
     "telemetry.EVAL_CONFIGS by its consumers (cli/--compare, harness, "
     "bench, quality ledger), documented in README, and closed-world vs "
     "the committed QUALITY_BASELINE.json parity keys")(check)
