"""Route-label rule (migrated from ``tools/check_route_labels.py``).

``serve/api.py`` folds unknown paths into the ``other`` route label; that
only works if every route a handler matches is in ``_ROUTES``, and the
``GET /debug`` index (``_DEBUG_INDEX``) is closed-world against the
``/debug/*`` routes, both directions.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, rule

API = "dllama_tpu/serve/api.py"


def _mentions_path(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("path", "_route"):
            return True
        if isinstance(sub, ast.Name) and sub.id == "path":
            return True
    return False


def _route_literals(node: ast.expr) -> list[str]:
    return [sub.value for sub in ast.walk(node)
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str)
            and sub.value.startswith("/")]


def check(project: Project, api_rel: str = API) -> tuple[list[Finding], str]:
    findings: list[Finding] = []

    def f(msg, lineno=0):
        findings.append(Finding("route-labels", api_rel, lineno, msg))

    sf = project.file(api_rel)
    if sf is None or sf.tree is None:
        f(f"{api_rel} missing or unparseable")
        return findings, ""

    routes: set[str] | None = None
    debug_index: dict | None = None
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "_ROUTES":
                    routes = set(ast.literal_eval(node.value))
                elif isinstance(tgt, ast.Name) and tgt.id == "_DEBUG_INDEX":
                    debug_index = ast.literal_eval(node.value)
    if routes is None:
        f("no _ROUTES assignment found")
        return findings, ""
    if debug_index is None:
        f("no _DEBUG_INDEX assignment found (the GET /debug index)")
        return findings, ""

    compared: set[str] = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left, *node.comparators]
        if not any(_mentions_path(s) for s in sides):
            continue
        for s in sides:
            if _mentions_path(s):
                continue
            for lit in _route_literals(s):
                compared.add(lit)
                if lit not in routes:
                    f(f"handler matches {lit!r} but it is not in "
                      f"_ROUTES — its traffic would be folded into the "
                      f"'other' label", node.lineno)

    debug_routes = {r for r in routes if r.startswith("/debug/")}
    for r in sorted(debug_routes - set(debug_index)):
        f(f"/debug route {r!r} has no _DEBUG_INDEX description — the "
          f"GET /debug index would silently omit it")
    for r in sorted(set(debug_index) - debug_routes):
        f(f"_DEBUG_INDEX entry {r!r} is not a registered /debug route "
          f"in _ROUTES")
    for r, desc in sorted(debug_index.items()):
        if not isinstance(desc, str) or not desc.strip():
            f(f"_DEBUG_INDEX[{r!r}] has an empty description")
    if "/debug" not in routes:
        f("the '/debug' index route itself is missing from _ROUTES")

    return findings, (f"route labels closed-world: {len(compared)} "
                      f"handler-matched routes all listed in _ROUTES "
                      f"({len(routes)} registered); GET /debug index "
                      f"covers all {len(debug_routes)} /debug routes")


rule("route-labels",
     "every handler-matched route is in serve/api.py _ROUTES; the "
     "/debug index is closed-world")(check)
