"""SLO objective-name rule.

The SLO observatory's objective vocabulary
(``dllama_tpu.runtime.slo.OBJECTIVES``) names the same thing in five
places: the ``--slo`` cli grammar, the ``/debug/slo`` body, the
``dllama_slo_*`` gauge labels, the fleet bench's ``slo`` section, and
the PERF.md / README.md docs. This rule keeps the vocabulary closed in
BOTH directions: every declared objective follows the grammar and is
documented everywhere, and every objective-shaped token anywhere in the
tree names a declared objective — a typo'd SLO name must fail lint, not
silently never alarm. Importing only the slo module keeps this runnable
without jax.
"""

from __future__ import annotations

import re
import sys

from .core import REPO, Finding, Project, rule

# the grammar each OBJECTIVES member must satisfy
GRAMMAR_RE = re.compile(r"^(?:(?:ttft|itl)_p\d{2}_ms|shed_rate)$")
# objective-shaped tokens in docs/source: the lookaround keeps composed
# identifiers (resume_ttft_p95_ms, ttft_ms_p95) from false-positiving
TOKEN_RE = re.compile(r"(?<![a-z0-9_])((?:ttft|itl)_p\d{2}_ms)(?!_)")

# where every objective must be spelled (the operator-facing contract)
DOC_FILES = ("PERF.md", "README.md", "dllama_tpu/serve/cli.py",
             "bench.py")
# where objective-shaped tokens are hunted for the reverse direction
SCAN_DIRS = ("dllama_tpu",)
SCAN_FILES = ("bench.py", "PERF.md", "README.md")


def _load_objectives():
    sys.path.insert(0, str(REPO))
    try:
        from dllama_tpu.runtime.slo import OBJECTIVES
    finally:
        sys.path.pop(0)
    return OBJECTIVES


def check(project: Project, objectives=None) -> tuple[list[Finding], str]:
    """``objectives`` injectable for fixture self-tests; defaults to the
    repo's live vocabulary."""
    objectives = (objectives if objectives is not None
                  else _load_objectives())
    findings: list[Finding] = []
    S = "dllama_tpu/runtime/slo.py"

    def f(path, msg, lineno=0):
        findings.append(Finding("slo-names", path, lineno, msg))

    for name in objectives:
        if not GRAMMAR_RE.match(name):
            f(S, f"objective {name!r} violates the SLO grammar "
                 f"((ttft|itl)_pNN_ms or shed_rate)")

    # forward: every objective spelled in each operator-facing file
    for rel in DOC_FILES:
        sf = project.file(rel)
        text = sf.text if sf is not None else ""
        for name in objectives:
            if name not in text:
                f(rel, f"SLO objective {name!r} is not mentioned in "
                       f"{rel} (grammar/docs drift)")

    # reverse: every objective-shaped token names a declared objective
    sources = [sf for sf in project.walk(*SCAN_DIRS)]
    for rel in SCAN_FILES:
        sf = project.file(rel)
        if sf is not None:
            sources.append(sf)
    for sf in sources:
        for lineno, line in enumerate(sf.lines, 1):
            for tok in TOKEN_RE.findall(line):
                if tok not in objectives:
                    f(sf.rel, f"token {tok!r} looks like an SLO "
                              f"objective but is not in slo.OBJECTIVES "
                              f"(typo, or extend the vocabulary)",
                      lineno)

    # the gauges the observatory publishes must be registered metrics
    sys.path.insert(0, str(REPO))
    try:
        from dllama_tpu.runtime.telemetry import SPECS
    finally:
        sys.path.pop(0)
    for metric in ("dllama_slo_compliance", "dllama_slo_burn_rate"):
        if metric not in SPECS:
            f("dllama_tpu/runtime/telemetry.py",
              f"SLO gauge {metric!r} is not registered in "
              f"telemetry.SPECS")

    return findings, (f"{len(objectives)} SLO objectives: grammar + "
                      f"docs + source tokens + gauges all consistent")


rule("slo-names",
     "every SLO objective name is grammar-clean, documented in the cli "
     "grammar / PERF.md / README.md / bench, and closed-world vs "
     "objective-shaped tokens")(check)
