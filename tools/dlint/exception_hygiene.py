"""Exception-hygiene rule (migrated from ``tools/check_exception_hygiene.py``).

The serving stack's fault-tolerance contract (ISSUE 2): no failure is
silently swallowed — a request either completes or its waiter gets an
explicit error. Bare ``except:`` is banned everywhere in ``dllama_tpu/``;
broad handlers in ``runtime/``/``serve/`` must re-raise, surface to a
waiter (``.error`` assignment, ``done.set``/``_fail_*``/``_on_crash``/
``os._exit``), or justify themselves with ``# noqa: BLE001 — reason``.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, rule

PKG = "dllama_tpu"
STRICT_DIRS = (f"{PKG}/runtime", f"{PKG}/serve")
_SURFACING_CALLS = {"_fail_all", "_fail_request", "_on_crash", "_exit"}


def _is_broad(node: ast.ExceptHandler) -> bool:
    def broad_name(t: ast.expr) -> bool:
        return isinstance(t, ast.Name) and t.id in ("Exception",
                                                    "BaseException")

    t = node.type
    if t is None:
        return False
    if broad_name(t):
        return True
    return isinstance(t, ast.Tuple) and any(broad_name(e) for e in t.elts)


def _walk_same_scope(stmts):
    """Walk without descending into nested defs — a ``raise`` inside a
    callback defined in the handler does not surface THIS failure."""
    todo = list(stmts)
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            todo.append(child)


def _handler_ok(node: ast.ExceptHandler, src_lines: list[str]) -> bool:
    line = src_lines[node.lineno - 1]
    if "noqa: BLE001" in line:
        return True
    for sub in _walk_same_scope(node.body):
        if isinstance(sub, ast.Raise):
            return True
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Attribute) and tgt.attr == "error":
                    return True
        if isinstance(sub, ast.Call):
            f = sub.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name in _SURFACING_CALLS:
                return True
            if (name == "set" and isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Attribute)
                    and f.value.attr == "done"):
                return True
    return False


def check(project: Project) -> tuple[list[Finding], str]:
    findings: list[Finding] = []
    n_handlers = 0
    files = project.walk(PKG)
    findings += project.parse_failures(files, "exception-hygiene")
    for sf in files:
        if sf.tree is None:
            continue
        strict = any(sf.rel.startswith(d + "/") for d in STRICT_DIRS)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(Finding(
                    "exception-hygiene", sf.rel, node.lineno,
                    "bare `except:` (catches KeyboardInterrupt/"
                    "SystemExit; name the exception)"))
                continue
            if strict and _is_broad(node):
                n_handlers += 1
                if not _handler_ok(node, sf.lines):
                    findings.append(Finding(
                        "exception-hygiene", sf.rel, node.lineno,
                        "`except Exception` must set a request .error, "
                        "re-raise, surface via done.set/_fail_*, or "
                        "carry `# noqa: BLE001 — <reason>` on the "
                        "except line"))
    return findings, (f"no bare excepts; {n_handlers} broad handlers in "
                      f"runtime/+serve/ all surface their failures")


rule("exception-hygiene",
     "no bare excepts; broad handlers in runtime//serve/ surface their "
     "failures to a waiter")(check)
