"""Trace-safety analyzer — the invariants every jitted program leans on.

Three rule families over ``dllama_tpu/`` (see LINTS.md for the catalog):

* ``jit-entry`` / ``shard-map-shim`` — the **closed-world jit entry**:
  every jit of a model-layer function goes through
  ``parallel.api.plan_scoped_jit`` (the per-engine trace-cache scope +
  compile-ledger hook), and every manual-SPMD entry goes through the
  ``parallel.api.shard_map`` version-compat shim. A raw spelling outside
  ``parallel/api.py`` is an error. ``ops/`` kernels are exempt from
  ``jit-entry`` by design: they are plan-independent (no ``constrain``
  in their bodies), so the plan-scoped cache argument does not apply.

* ``tracer-host-sync`` / ``tracer-ambient`` / ``tracer-branch`` —
  **tracer hazards inside traced function bodies**. Traced functions are
  found by reachability: every function handed to
  ``plan_scoped_jit``/``jax.jit``/``shard_map`` anywhere in the package
  is a root; a name-based call graph over ``models/``, ``ops/`` and
  ``parallel/`` closes the set. Inside a traced body:

  - host syncs — ``.item()``, ``float()/int()/bool()`` casts or
    ``np.asarray``/``np.array`` on a *traced* value — block the dispatch
    pipeline (or crash on non-concrete tracers);
  - ambient host state — ``time.*``, ``np.random.*``, ``random.*``,
    ``datetime.*`` — silently bakes one trace-time value into the
    compiled program;
  - Python branching (``if``/``while``/``assert``/ternary) on a traced
    value raises ``TracerBoolConversionError`` at trace time — on
    whichever backend first traces that path, which for multihost/TPU
    branches may be the one machine CI never runs.

  Traced-vs-static telling: the repo's STATIC-trace-config convention —
  ``cfg``-style config objects, mesh plans, ``n_*`` counts, shape/axis/
  impl-string parameters are trace-time constants (static_argnums);
  everything else flowing in is a tracer. Metadata reads
  (``.shape``/``.ndim``/``.dtype``, ``len()``) and ``is None`` checks on
  tracers are static and stay allowed.

* ``guarded-twin`` — **tripwire completeness** (the PR5 contract): every
  decode-program in the ``*_step``/``*_steps`` family
  (``models/llama.py``) and the replicated multihost family
  (``parallel/multihost.py``) must have its ``*_guarded`` twin, or the
  non-finite tripwire has a blind spot exactly where an engine could
  dispatch.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Project, SourceFile, rule

PKG = "dllama_tpu"
TRACED_DIRS = (f"{PKG}/models", f"{PKG}/ops", f"{PKG}/parallel")
SHIM = f"{PKG}/parallel/api.py"


# -- helpers ------------------------------------------------------------------

def dotted(node: ast.expr) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_dlint_path(rel: str) -> bool:
    return rel.replace("\\", "/").startswith("tools/dlint/")


# -- rule: jit-entry ----------------------------------------------------------

# model-layer dirs where a jit can bake a mesh plan into its trace
_JIT_SCOPE = (f"{PKG}/models", f"{PKG}/runtime", f"{PKG}/serve",
              f"{PKG}/parallel", f"{PKG}/tokenizer", f"{PKG}/convert",
              f"{PKG}/formats")
_RAW_JIT = {"jax.jit", "jax.pjit", "pjit", "jax.experimental.pjit.pjit"}


@rule("jit-entry",
      "model-layer jit goes through parallel.api.plan_scoped_jit "
      "(closed-world per-engine trace cache + compile ledger)")
def check_jit_entry(project: Project):
    findings: list[Finding] = []
    files = [sf for sf in project.walk(*_JIT_SCOPE) if sf.rel != SHIM]
    findings += project.parse_failures(files, "jit-entry")
    n = 0
    for sf in files:
        if sf.tree is None:
            continue
        n += 1
        for node in ast.walk(sf.tree):
            name = None
            if isinstance(node, (ast.Attribute, ast.Name)):
                name = dotted(node)
            if name in _RAW_JIT:
                findings.append(Finding(
                    "jit-entry", sf.rel, node.lineno,
                    f"raw {name!r} — jit model-layer functions through "
                    f"parallel.api.plan_scoped_jit (per-engine trace "
                    f"cache, compile-ledger hook); see LINTS.md"))
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = getattr(node, "module", "") or ""
                for alias in node.names:
                    if (alias.name == "pjit" or "pjit" in mod):
                        findings.append(Finding(
                            "jit-entry", sf.rel, node.lineno,
                            f"import of pjit ({mod or alias.name}) — "
                            f"route jit through parallel.api"))
    return findings, (f"{n} model-layer files: every jit goes through "
                      f"plan_scoped_jit")


# -- rule: shard-map-shim (migrated from tools/check_shard_map_shim.py) -------

_RAW_SHARD_RE = re.compile(
    r"(jax\.shard_map"
    r"|jax\.experimental\.shard_map"
    r"|from\s+jax\.experimental\.shard_map\s+import"
    r"|from\s+jax\.experimental\s+import\s+shard_map)")


@rule("shard-map-shim",
      "every shard_map call site goes through parallel.api's "
      "version-compat shim")
def check_shard_map_shim(project: Project):
    """The top-level ``jax.shard_map`` does not exist on 0.4.x jax and
    ``jax.experimental.shard_map`` is gone on >= 0.5 — a raw call site
    can never trace on one of the two (the root cause of the 13 seed
    qcollectives failures; CHANGES.md PR2)."""
    findings: list[Finding] = []
    n = 0
    for sf in project.walk(PKG, "tests", "tools"):
        if sf.rel == SHIM or _is_dlint_path(sf.rel) \
                or sf.rel == "tools/check_shard_map_shim.py":
            continue
        n += 1
        for lineno, line in sf.code_lines():
            m = _RAW_SHARD_RE.search(line)
            if m:
                findings.append(Finding(
                    "shard-map-shim", sf.rel, lineno,
                    f"raw {m.group(0)!r} — route manual SPMD through "
                    f"dllama_tpu.parallel.api.shard_map (the version-"
                    f"compat shim); a raw call cannot trace on every "
                    f"supported jax"))
    return findings, (f"{n} files: every shard_map call site goes through "
                      f"parallel.api's version-compat shim")


# -- traced-function discovery ------------------------------------------------

_JIT_WRAPPERS = {"plan_scoped_jit", "jit", "shard_map"}
# static reads on traced values: array metadata, plus shape-derived
# properties and pytree AUX fields this repo declares static under jit
# (QuantizedWeight.out_features is codes.shape-derived; TurboWeight.a8
# is aux data — "a static under jit", ops/turbo.py)
_METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                   "itemsize", "out_features", "a8"}
_STATIC_NAMES = {"cfg", "config", "plan", "mesh", "self", "impl", "axis",
                 "axis_name", "axis_names", "interpret", "fast", "bn", "bk",
                 "block_size", "unroll", "site", "sites", "program", "scope",
                 "k"}
_STATIC_PREFIXES = ("n_", "is_", "use_", "num_")
_STATIC_SUFFIXES = ("_shape", "_size", "_axis", "_name", "_impl", "_dtype",
                    "_logical", "_axes", "_specs", "_spec", "_steps",
                    "_type")
_STATIC_ANNOT = ("Config", "int", "str", "bool", "Mesh", "MeshPlan",
                 "Plan")
_AMBIENT_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                     "datetime.")
_HOST_CASTS = {"float", "int", "bool", "complex"}
_NP_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
            "np.copy", "numpy.copy"}
_SAFE_CALLS = {"len", "isinstance", "hasattr", "getattr", "type", "range",
               "print", "repr", "str", "tuple", "min", "max",
               "jax.ShapeDtypeStruct"}

# `# dlint: static-fn` on a def line declares a host gate whose return
# value is a trace-time constant (shape/dtype/env decisions only) — its
# call results stay untainted. The rule harvests these from the traced
# dirs; LINTS.md documents the contract the annotation asserts.
STATIC_FN_RE = re.compile(r"#\s*dlint:\s*static-fn")


def _param_is_static(name: str, annot: str) -> bool:
    if name in _STATIC_NAMES:
        return True
    if name.startswith(_STATIC_PREFIXES) or name.endswith(_STATIC_SUFFIXES):
        return True
    return any(a in annot for a in _STATIC_ANNOT)


def _annot_str(a: ast.expr | None) -> str:
    if a is None:
        return ""
    try:
        return ast.unparse(a)
    except Exception:  # pragma: no cover - unparse is total on parsed trees
        return ""


class _FnIndex:
    """Module-level function defs across the traced dirs, by bare name
    (collisions merge — reachability stays conservative)."""

    def __init__(self, files: list[SourceFile]):
        self.defs: dict[str, list[tuple[SourceFile, ast.FunctionDef]]] = {}
        for sf in files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.defs.setdefault(node.name, []).append((sf, node))

    def called_names(self, fn: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name):
                    out.add(f.id)
                elif isinstance(f, ast.Attribute):
                    out.add(f.attr)
            # a function passed by reference (e.g. a lax.scan body or a
            # step1 callback) is traced too
            elif isinstance(node, ast.Name) and not isinstance(
                    getattr(node, "ctx", None), ast.Store):
                if node.id in self.defs:
                    out.add(node.id)
        return out


def _jit_roots(project: Project) -> set[str]:
    """Names of functions handed to plan_scoped_jit/jax.jit/shard_map
    anywhere in the package (call args + jit decorators, including
    ``@functools.partial(jax.jit, ...)``)."""
    roots: set[str] = set()
    for sf in project.walk(PKG):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                fname = dotted(node.func)
                tail = fname.rsplit(".", 1)[-1] if fname else None
                if tail in _JIT_WRAPPERS and node.args:
                    name = dotted(node.args[0])
                    if name:
                        roots.add(name.rsplit(".", 1)[-1])
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    names = {dotted(n) for n in ast.walk(dec)
                             if isinstance(n, (ast.Attribute, ast.Name))}
                    if any(n and (n == "jit" or n.endswith(".jit"))
                           for n in names):
                        roots.add(node.name)
    return roots


def traced_functions(project: Project):
    """(SourceFile, FunctionDef) pairs reachable from the jit roots,
    restricted to models//ops//parallel/."""
    files = [sf for sf in project.walk(*TRACED_DIRS)]
    index = _FnIndex(files)
    reach: set[str] = set()
    frontier = [r for r in _jit_roots(project) if r in index.defs]
    while frontier:
        name = frontier.pop()
        if name in reach:
            continue
        reach.add(name)
        for _, node in index.defs.get(name, ()):
            for callee in index.called_names(node):
                if callee in index.defs and callee not in reach:
                    frontier.append(callee)
    out = []
    for name in sorted(reach):
        out.extend(index.defs[name])
    return out


# -- taint walk ---------------------------------------------------------------

def _static_fns(files: list[SourceFile]) -> set[str]:
    """Names of functions annotated ``# dlint: static-fn`` (def line or
    the line above) across the traced dirs."""
    out: set[str] = set()
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for lineno in (node.lineno, node.lineno - 1):
                if 1 <= lineno <= len(sf.lines) and \
                        STATIC_FN_RE.search(sf.lines[lineno - 1]):
                    out.add(node.name)
    return out


class _Taint:
    """Order-sensitive single-pass taint over one function body: params
    not matching the STATIC conventions are tracers; assignment from a
    tainted expression taints the target; metadata reads and declared
    static-fn calls un-taint."""

    def __init__(self, fn: ast.FunctionDef, inherited: set[str],
                 static_fns: set[str] = frozenset()):
        self.static_fns = set(static_fns)
        self.tainted = set(inherited)
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if not _param_is_static(a.arg, _annot_str(a.annotation)):
                self.tainted.add(a.arg)

    def expr(self, node: ast.expr | None) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _METADATA_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            if fname in _SAFE_CALLS:
                return False
            if fname and fname.rsplit(".", 1)[-1] in self.static_fns:
                return False
            parts = ([self.expr(a) for a in node.args]
                     + [self.expr(kw.value) for kw in node.keywords])
            # a method call on a tainted object yields a tainted result
            if isinstance(node.func, ast.Attribute):
                parts.append(self.expr(node.func.value))
            return any(parts)
        if isinstance(node, ast.Compare):
            return self.expr(node.left) or any(
                self.expr(c) for c in node.comparators)
        if isinstance(node, (ast.BoolOp, ast.BinOp, ast.UnaryOp, ast.IfExp,
                             ast.Tuple, ast.List, ast.Set, ast.Starred,
                             ast.Subscript, ast.Slice, ast.JoinedStr,
                             ast.FormattedValue, ast.Dict)):
            return any(self.expr(c) for c in ast.iter_child_nodes(node)
                       if isinstance(c, ast.expr))
        return False

    def assign_targets(self, target: ast.expr) -> list[str]:
        out = []
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                out.append(node.id)
        return out

    def mark(self, target: ast.expr, value_tainted: bool) -> None:
        for name in self.assign_targets(target):
            if value_tainted:
                self.tainted.add(name)
            else:
                self.tainted.discard(name)


def _is_none_check(test: ast.expr) -> bool:
    """``x is None`` / ``x is not None`` (possibly or-ed): static-ness
    checks on optional tracers are trace-time constants."""
    if isinstance(test, ast.BoolOp):
        return all(_is_none_check(v) for v in test.values)
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
            and all(isinstance(c, ast.Constant) and c.value is None
                    for c in test.comparators))


def _branch_tainted(taint: _Taint, test: ast.expr) -> bool:
    """Branch-condition taint with none-check pruning: in
    ``res is None and force`` the tracer only appears inside the
    ``is None`` (a static check), so the branch is trace-safe."""
    if _is_none_check(test):
        return False
    if isinstance(test, ast.BoolOp):
        return any(_branch_tainted(taint, v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _branch_tainted(taint, test.operand)
    return taint.expr(test)


def _scan_traced_body(sf: SourceFile, fn: ast.FunctionDef,
                      inherited: set[str],
                      findings: list[Finding],
                      static_fns: set[str] = frozenset()) -> None:
    taint = _Taint(fn, inherited, static_fns)

    def hazard_calls(node: ast.Call) -> None:
        fname = dotted(node.func)
        if fname:
            if fname.startswith(_AMBIENT_PREFIXES):
                findings.append(Finding(
                    "tracer-ambient", sf.rel, node.lineno,
                    f"{fname}() inside traced function "
                    f"{fn.name!r} bakes one trace-time value into the "
                    f"compiled program (ambient host state)"))
                return
            if fname in _NP_SYNC and any(
                    taint.expr(a) for a in node.args):
                findings.append(Finding(
                    "tracer-host-sync", sf.rel, node.lineno,
                    f"{fname}() on a traced value inside {fn.name!r} "
                    f"forces a device→host sync (or crashes on an "
                    f"abstract tracer)"))
                return
            if fname in _HOST_CASTS and any(
                    taint.expr(a) for a in node.args):
                findings.append(Finding(
                    "tracer-host-sync", sf.rel, node.lineno,
                    f"{fname}() cast of a traced value inside "
                    f"{fn.name!r} forces a host sync "
                    f"(ConcretizationTypeError on an abstract tracer)"))
                return
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            findings.append(Finding(
                "tracer-host-sync", sf.rel, node.lineno,
                f".item() inside traced function {fn.name!r} is a "
                f"device→host sync"))

    def scan_exprs(st: ast.stmt) -> None:
        """Hazard scan over the statement's own expression fields (block
        bodies are statement lists and recurse separately; nested defs
        are re-scanned with their own taint frame). Lambdas stay in the
        walk — a lambda inside a traced body is traced too."""
        for field, value in ast.iter_fields(st):
            exprs = [value] if isinstance(value, ast.expr) else [
                v for v in (value if isinstance(value, list) else [])
                if isinstance(v, ast.expr)]
            if isinstance(value, list):  # `with a, b:` items
                exprs += [v.context_expr for v in value
                          if isinstance(v, ast.withitem)]
            for e in exprs:
                for node in ast.walk(e):
                    if isinstance(node, ast.Call):
                        hazard_calls(node)
                    elif isinstance(node, ast.IfExp) and \
                            _branch_tainted(taint, node.test):
                        findings.append(Finding(
                            "tracer-branch", sf.rel, node.lineno,
                            f"ternary on a traced value inside "
                            f"{fn.name!r} — use jnp.where"))

    def visit(stmts: list[ast.stmt]) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_traced_body(sf, st, set(taint.tainted), findings,
                                  static_fns)
                continue
            if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                t = taint.expr(st.value)
                if isinstance(st, ast.AugAssign):
                    t = t or taint.expr(st.target)
                targets = (st.targets if isinstance(st, ast.Assign)
                           else [st.target])
                for tgt in targets:
                    taint.mark(tgt, t)
            if isinstance(st, (ast.If, ast.While)):
                if _branch_tainted(taint, st.test):
                    findings.append(Finding(
                        "tracer-branch", sf.rel, st.lineno,
                        f"Python branch on a traced value inside "
                        f"{fn.name!r} — TracerBoolConversionError at "
                        f"trace time (use lax.cond/jnp.where, or make "
                        f"the input STATIC trace config)"))
            if isinstance(st, ast.Assert) and \
                    _branch_tainted(taint, st.test):
                findings.append(Finding(
                    "tracer-branch", sf.rel, st.lineno,
                    f"assert on a traced value inside {fn.name!r} — "
                    f"TracerBoolConversionError at trace time (assert "
                    f"on .shape/.ndim metadata instead)"))
            if isinstance(st, ast.For) and taint.expr(st.iter):
                taint.mark(st.target, True)
            scan_exprs(st)
            # recurse into block bodies with the running taint state
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    visit(sub)
            for h in getattr(st, "handlers", []) or []:
                visit(h.body)

    visit(fn.body)


@rule("tracer-hazard",
      "traced function bodies are free of host syncs, ambient host "
      "state, and Python branches on traced values")
def check_tracer_hazards(project: Project):
    findings: list[Finding] = []
    fns = traced_functions(project)
    static_fns = _static_fns([sf for sf in project.walk(*TRACED_DIRS)])
    for sf, fn in fns:
        _scan_traced_body(sf, fn, set(), findings, static_fns)
    return findings, (f"{len(fns)} traced functions (call-graph closure "
                      f"of every jit/shard_map root): no host syncs, no "
                      f"ambient state, no tracer branches "
                      f"({len(static_fns)} declared static-fn gates)")


# -- rule: guarded-twin -------------------------------------------------------

_LLAMA = f"{PKG}/models/llama.py"
_MULTIHOST = f"{PKG}/parallel/multihost.py"


def _module_defs(sf: SourceFile) -> dict[str, int]:
    out: dict[str, int] = {}
    if sf.tree is None:
        return out
    for node in sf.tree.body:  # module level only
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node.lineno
    return out


@rule("guarded-twin",
      "every decode program in the *_step family has its _guarded "
      "tripwire twin (PR5 contract)")
def check_guarded_twins(project: Project):
    findings: list[Finding] = []
    checked = 0

    def family(sf: SourceFile, member) -> None:
        nonlocal checked
        defs = _module_defs(sf)
        for name, lineno in sorted(defs.items()):
            if name.startswith("_") or name.endswith("_guarded"):
                continue
            if "forward" in name or not member(name):
                continue
            checked += 1
            if f"{name}_guarded" not in defs:
                findings.append(Finding(
                    "guarded-twin", sf.rel, lineno,
                    f"decode program {name!r} has no {name}_guarded twin "
                    f"— the non-finite tripwire (PR5) cannot ride its "
                    f"dispatches; add the twin next to it"))

    llama = project.file(_LLAMA)
    if llama is not None:
        family(llama, lambda n: n.endswith(("_step", "_steps"))
               or n in ("greedy_step", "sampled_step"))
    elif project.file(PKG) is not None:  # pragma: no cover
        findings.append(Finding("guarded-twin", _LLAMA, 0, "file missing"))
    mh = project.file(_MULTIHOST)
    if mh is not None:
        family(mh, lambda n: n.startswith("replicated_"))
    return findings, (f"{checked} decode-family programs all have their "
                      f"_guarded tripwire twins")
