"""``python -m tools.dlint`` — run the repo's static-analysis rules.

Exit 0 on a clean repo. ``--only RULE[,RULE...]`` selects rules,
``--json`` prints the one-line machine summary CI consumes, ``--list``
names every registered rule, ``--root PATH`` points at a different tree
(the fixture self-tests use this).
"""

from __future__ import annotations

import argparse
import sys

if __package__ in (None, ""):  # `python tools/dlint/__main__.py` direct run
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[2]))
    from tools.dlint.core import Project, all_rules, run_rules
else:
    from .core import Project, all_rules, run_rules


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.dlint",
        description="unified AST static analysis (see LINTS.md)")
    p.add_argument("--only", default=None, metavar="RULE[,RULE...]",
                   help="run only these comma-separated rules")
    p.add_argument("--json", action="store_true", dest="json_out",
                   help="one-line JSON summary (CI consumption)")
    p.add_argument("--list", action="store_true",
                   help="list registered rules and exit")
    p.add_argument("--root", default=None,
                   help="analyze this tree instead of the repo")
    args = p.parse_args(argv)

    if args.list:
        for name, r in sorted(all_rules().items()):
            print(f"{name:24s} {r.doc}")
        return 0

    project = Project(args.root) if args.root else Project()
    only = ([s.strip() for s in args.only.split(",") if s.strip()]
            if args.only else None)
    return run_rules(project, only=only, json_out=args.json_out)


if __name__ == "__main__":
    sys.exit(main())
