"""Metric-name rule (migrated from ``tools/check_metrics_names.py``).

Closed-world in BOTH directions against the single declaration point
(``dllama_tpu.runtime.telemetry.SPECS``): naming convention, PERF.md
documentation, no orphaned source literals, no stale doc mentions.
Importing only the telemetry module keeps this runnable without jax.
"""

from __future__ import annotations

import re
import sys

from .core import REPO, Finding, Project, rule

NAME_RE = re.compile(r"^dllama_[a-z0-9_]+$")
LITERAL_RE = re.compile(r"""["'](dllama_[a-z0-9_]+)["']""")
TOKEN_RE = re.compile(r"\b(dllama_[a-z0-9_]+)")
NOT_METRICS = {"dllama_tpu"}
NOT_METRIC_PREFIXES = ("dllama_model_",)


def _not_a_metric(lit: str) -> bool:
    return lit in NOT_METRICS or lit.startswith(NOT_METRIC_PREFIXES)


def _load_specs():
    sys.path.insert(0, str(REPO))
    try:
        from dllama_tpu.runtime.telemetry import SPECS
    finally:
        sys.path.pop(0)
    return SPECS


def check(project: Project, specs=None) -> tuple[list[Finding], str]:
    """``specs`` injectable for fixture self-tests; defaults to the
    repo's live telemetry registry."""
    specs = specs if specs is not None else _load_specs()
    findings: list[Finding] = []
    T = "dllama_tpu/runtime/telemetry.py"

    def f(path, msg, lineno=0):
        findings.append(Finding("metrics-names", path, lineno, msg))

    for name, spec in specs.items():
        if not NAME_RE.match(name):
            f(T, f"registered metric {name!r} violates "
                 f"dllama_[a-z0-9_]+ naming")
        if spec.kind not in ("counter", "gauge", "histogram"):
            f(T, f"{name}: unknown kind {spec.kind!r}")
        if spec.kind == "counter" and not name.endswith("_total"):
            f(T, f"counter {name} must end in _total "
                 f"(Prometheus convention)")
        if not spec.help:
            f(T, f"{name}: empty help text")

    perf_sf = project.file("PERF.md")
    perf = perf_sf.text if perf_sf is not None else ""
    for name in specs:
        if name not in perf:
            f("PERF.md", f"metric {name} is not documented in PERF.md")

    derived = {base + suffix for base, spec in specs.items()
               if spec.kind == "histogram"
               for suffix in ("_bucket", "_sum", "_count")}
    for name in sorted(set(LITERAL_RE.findall(perf))
                       | set(TOKEN_RE.findall(perf))):
        if _not_a_metric(name) or name in specs or name in derived:
            continue
        f("PERF.md", f"PERF.md mentions {name!r} but no such metric "
                     f"family is registered in telemetry.SPECS "
                     f"(stale doc or typo)")

    for sf in project.walk("dllama_tpu"):
        for lineno, line in enumerate(sf.lines, 1):
            for lit in LITERAL_RE.findall(line):
                if _not_a_metric(lit) or lit in specs:
                    continue
                f(sf.rel, f"literal {lit!r} looks like a metric name "
                          f"but is not registered in telemetry.SPECS",
                  lineno)

    return findings, (f"{len(specs)} metric names: convention + PERF.md "
                      f"docs + source literals all consistent")


rule("metrics-names",
     "every telemetry metric name is convention-clean, documented in "
     "PERF.md, and closed-world vs source literals")(check)
