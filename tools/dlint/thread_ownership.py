"""Thread-ownership analyzer — who may run what, and under which lock.

The serving stack's thread model (``runtime/serving.py`` docstrings): ONE
loop thread owns the generator and the ``BlockPool``; HTTP handler
threads only submit/wait; the watchdog's monitor thread supervises a
wedged loop thread from outside. The PR6 review caught — by hand — a
monitor-thread path reaching a loop-thread-owned pool mutator; these
rules make that class of bug machine-checked.

Grammar (annotations live in the code, next to the methods they describe):

* ``# dlint: owner=loop-thread|monitor-thread|probe-thread|any`` on (or
  directly above) a ``def`` line declares which thread may run the
  method. ``loop-thread`` = only the scheduler's loop thread;
  ``monitor-thread`` = the watchdog monitor; ``probe-thread`` = a fleet
  router replica's health-probe thread (serve/router.py); ``any`` = any
  thread (handler threads, the closer, the monitor) — so an ``any``
  method may never reach a ``loop-thread`` one either.
* ``# dlint: guarded-by=_lock`` on a ``self.X = ...`` line in
  ``__init__`` declares that writes/mutations of ``self.X`` outside
  ``__init__`` must happen inside ``with self._lock:``.

Rules:

* ``thread-ownership`` — call-graph check: from every method owned by
  ``monitor-thread``, ``probe-thread``, or ``any``, no transitive call
  path (name-resolved over the annotated files; unannotated methods are
  pass-through) may reach a ``loop-thread``-owned method. The entry
  points the PR6 bug class lives in (``_on_stall``, ``_on_crash``,
  ``_fail_all``) must be annotated at all.
* ``lock-guard`` — declared-guarded attributes are only written (assign,
  augment, or mutate via ``append``/``pop``/``clear``/...) under their
  lock, outside ``__init__``.
* ``lock-order`` — over ``dllama_tpu/runtime/``: build the
  lock-acquisition-order graph (holding ``A._lock`` while a reachable
  callee takes ``B._lock`` adds edge A→B) and reject cycles — including
  self-edges, since every lock here is a non-reentrant
  ``threading.Lock``.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Project, SourceFile, rule

PKG = "dllama_tpu"
OWNED_FILES = (f"{PKG}/runtime/serving.py", f"{PKG}/runtime/watchdog.py",
               f"{PKG}/runtime/kvblocks.py", f"{PKG}/serve/router.py")
RUNTIME_DIR = f"{PKG}/runtime"

OWNER_RE = re.compile(
    r"#\s*dlint:\s*owner=(loop-thread|monitor-thread|probe-thread|any)")
GUARDED_RE = re.compile(r"#\s*dlint:\s*guarded-by=([A-Za-z_][A-Za-z0-9_]*)")

# entry points that MUST carry an owner annotation: the supervision
# paths where the PR6 class of bug lives
REQUIRED_OWNERS = {"_on_stall", "_on_crash", "_fail_all"}

_MUTATORS = {"append", "pop", "insert", "remove", "clear", "extend",
             "update", "popitem", "add", "discard", "setdefault", "sort",
             "appendleft", "popleft"}


# -- annotation harvesting ----------------------------------------------------

class _Method:
    def __init__(self, sf: SourceFile, cls: str | None,
                 node: ast.FunctionDef, owner: str | None):
        self.sf = sf
        self.cls = cls
        self.node = node
        self.owner = owner  # None = unannotated (pass-through)

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.node.name}" if self.cls else self.node.name


def _owner_for(sf: SourceFile, node: ast.FunctionDef) -> str | None:
    """owner= on the def line or the line directly above it (above the
    decorators, when present)."""
    first = min([node.lineno]
                + [d.lineno for d in node.decorator_list])
    for lineno in (node.lineno, first, first - 1):
        if 1 <= lineno <= len(sf.lines):
            m = OWNER_RE.search(sf.lines[lineno - 1])
            if m:
                return m.group(1)
    return None


def harvest_methods(project: Project,
                    rel_files=OWNED_FILES) -> list[_Method]:
    out: list[_Method] = []
    for rel in rel_files:
        sf = project.file(rel)
        if sf is None or sf.tree is None:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        out.append(_Method(sf, node.name, sub,
                                           _owner_for(sf, sub)))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(_Method(sf, None, node, _owner_for(sf, node)))
    return out


def _called_method_names(fn: ast.AST) -> set[str]:
    """Names invoked as calls: ``self.x()``, ``obj.attr.x()``, ``x()``."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                out.add(f.attr)
            elif isinstance(f, ast.Name):
                out.add(f.id)
    return out


# -- rule: thread-ownership ---------------------------------------------------

@rule("thread-ownership",
      "monitor-thread/any supervision paths never reach loop-thread-"
      "owned pool mutators")
def check_thread_ownership(project: Project):
    findings: list[Finding] = []
    methods = harvest_methods(project)
    if not methods:
        return findings, "no owned files (nothing to check)"
    by_name: dict[str, list[_Method]] = {}
    for m in methods:
        by_name.setdefault(m.node.name, []).append(m)

    # annotation completeness for the supervision entry points
    annotated = 0
    for m in methods:
        if m.owner is not None:
            annotated += 1
        elif m.node.name in REQUIRED_OWNERS:
            findings.append(Finding(
                "thread-ownership", m.sf.rel, m.node.lineno,
                f"{m.qual} is a supervision entry point and must carry "
                f"a `# dlint: owner=...` annotation"))

    # transitive reachability per entry point: a fresh BFS each time —
    # exact under call-graph cycles and entry-specific trails. (A memo
    # shared across entries is unsound here: results computed under a
    # cycle cut are incomplete, and cached trails belong to the FIRST
    # root that explored them. The graphs are dozens of nodes; exactness
    # beats caching.) Unannotated methods are pass-through; loop-thread
    # methods terminate the walk — inside the loop thread everything is
    # legal.
    def reach_loop_owned(entry: _Method) -> dict[str, tuple[str, ...]]:
        hits: dict[str, tuple[str, ...]] = {}
        seen: set[int] = {id(entry)}
        frontier: list[tuple[_Method, tuple[str, ...]]] = [
            (entry, (entry.qual,))]
        while frontier:
            m, trail = frontier.pop()
            for callee_name in sorted(_called_method_names(m.node)):
                for callee in by_name.get(callee_name, ()):
                    t = trail + (callee.qual,)
                    if callee.owner == "loop-thread":
                        hits.setdefault(callee.qual, t)
                    elif callee.owner is None and id(callee) not in seen:
                        seen.add(id(callee))
                        frontier.append((callee, t))
        return hits

    for m in methods:
        if m.owner not in ("monitor-thread", "probe-thread", "any"):
            continue
        hits = reach_loop_owned(m)
        for target, trail in sorted(hits.items()):
            findings.append(Finding(
                "thread-ownership", m.sf.rel, m.node.lineno,
                f"{m.qual} (owner={m.owner}) reaches loop-thread-owned "
                f"{target} via {' -> '.join(trail)} — supervision "
                f"threads must never touch loop-thread state (the PR6 "
                f"pool-mutation bug class)"))
    return findings, (f"{annotated} owner-annotated methods across "
                      f"{len(OWNED_FILES)} files; no monitor/any path "
                      f"reaches loop-thread state")


# -- rule: lock-guard ---------------------------------------------------------

def _guarded_attrs(sf: SourceFile,
                   cls: ast.ClassDef) -> dict[str, str]:
    """attr -> lock attr, from guarded-by annotations in __init__."""
    out: dict[str, str] = {}
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            for sub in ast.walk(node):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                m = GUARDED_RE.search(sf.lines[sub.lineno - 1]) \
                    if sub.lineno <= len(sf.lines) else None
                if not m:
                    continue
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for tgt in targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        out[tgt.attr] = m.group(1)
    return out


def _with_locks(node: ast.With) -> set[str]:
    """Lock attr names taken by ``with self.<lock>:`` items."""
    out = set()
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Attribute) and \
                isinstance(ctx.value, ast.Name) and ctx.value.id == "self":
            out.add(ctx.attr)
    return out


def _self_attr(node) -> str | None:
    """``self.X`` / ``self.X[...]`` -> ``X``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    if isinstance(node, ast.Subscript):
        return _self_attr(node.value)
    return None


def _check_method_guards(sf: SourceFile, cls_name: str,
                         fn: ast.FunctionDef, guarded: dict[str, str],
                         findings: list[Finding]) -> None:
    def flag(node, attr, held) -> None:
        lock = guarded.get(attr)
        if lock is not None and lock not in held:
            findings.append(Finding(
                "lock-guard", sf.rel, node.lineno,
                f"{cls_name}.{fn.name} writes self.{attr} outside "
                f"`with self.{lock}` (declared guarded-by={lock} in "
                f"__init__)"))

    def check_stmt(st: ast.stmt, held: frozenset[str]) -> None:
        """Writes/mutations in this statement's own expressions (block
        bodies recurse separately with their held-set)."""
        if isinstance(st, ast.Assign):
            for tgt in st.targets:
                attr = _self_attr(tgt)
                if attr:
                    flag(st, attr, held)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            attr = _self_attr(st.target)
            if attr:
                flag(st, attr, held)
        for field, value in ast.iter_fields(st):
            exprs = [value] if isinstance(value, ast.expr) else [
                v for v in (value if isinstance(value, list) else [])
                if isinstance(v, ast.expr)]
            for e in exprs:
                for node in ast.walk(e):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr in _MUTATORS:
                        attr = _self_attr(node.func.value)
                        if attr:
                            flag(node, attr, held)

    def visit(stmts, held: frozenset[str]) -> None:
        for st in stmts:
            if isinstance(st, ast.With):
                visit(st.body, held | frozenset(_with_locks(st)))
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs where it is CALLED; lexically it is
                # almost always invoked in place (closures like
                # _go_unready) — check with the held-set of its own body
                visit(st.body, frozenset())
                continue
            check_stmt(st, held)
            for attr_name in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr_name, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    visit(sub, held)
            for h in getattr(st, "handlers", []) or []:
                visit(h.body, held)

    visit(fn.body, frozenset())


@rule("lock-guard",
      "declared-guarded shared attributes are only written under their "
      "lock")
def check_lock_guard(project: Project):
    findings: list[Finding] = []
    n_attrs = 0
    for rel in OWNED_FILES:
        sf = project.file(rel)
        if sf is None or sf.tree is None:
            continue
        for cls in sf.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = _guarded_attrs(sf, cls)
            if not guarded:
                continue
            n_attrs += len(guarded)
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and fn.name != "__init__":
                    _check_method_guards(sf, cls.name, fn, guarded,
                                         findings)
    return findings, (f"{n_attrs} guarded-by attributes: every write "
                      f"holds the declared lock")


# -- rule: lock-order ---------------------------------------------------------

class _LockGraph:
    """Classes in runtime/ that own ``threading.Lock`` attrs, the
    name-based call graph between their methods, and the
    holds-A-acquires-B edge set.

    Call resolution is deliberately conservative about noise:
    ``self.x()`` resolves within the calling class first (falling back
    to every class defining ``x``); ``obj.x()`` resolves only when
    exactly one class in runtime/ defines ``x`` — an ambiguous name
    (``close``, which files and schedulers both have) would otherwise
    fabricate edges between unrelated locks."""

    def __init__(self, project: Project):
        self.methods: dict[str, list[tuple[str, ast.FunctionDef]]] = {}
        self.by_class: dict[str, dict[str, ast.FunctionDef]] = {}
        self.class_locks: dict[str, set[str]] = {}
        self.files: list[SourceFile] = project.walk(RUNTIME_DIR)
        for sf in self.files:
            if sf.tree is None:
                continue
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            self.methods.setdefault(sub.name, []).append(
                                (node.name, sub))
                            self.by_class.setdefault(
                                node.name, {})[sub.name] = sub
                            if sub.name == "__init__":
                                self._harvest_locks(node.name, sub)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self.methods.setdefault(node.name, []).append(
                        ("", node))
        self._trans: dict[int, set[str]] | None = None

    def resolve(self, caller_cls: str,
                call: ast.Call) -> list[tuple[str, ast.FunctionDef]]:
        f = call.func
        if isinstance(f, ast.Attribute):
            name = f.attr
            on_self = (isinstance(f.value, ast.Name)
                       and f.value.id == "self")
            if on_self and name in self.by_class.get(caller_cls, {}):
                return [(caller_cls, self.by_class[caller_cls][name])]
            cands = self.methods.get(name, [])
            if on_self:
                return cands
            return cands if len(cands) == 1 else []
        if isinstance(f, ast.Name):
            cands = self.methods.get(f.id, [])
            return [c for c in cands if c[0] == ""] or (
                cands if len(cands) == 1 else [])
        return []

    def _harvest_locks(self, cls: str, init: ast.FunctionDef) -> None:
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                fname = None
                f = node.value.func
                if isinstance(f, ast.Attribute):
                    fname = f.attr
                elif isinstance(f, ast.Name):
                    fname = f.id
                if fname not in ("Lock", "RLock"):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        self.class_locks.setdefault(cls, set()).add(tgt.attr)

    def lock_id(self, cls: str, attr: str) -> str | None:
        if attr in self.class_locks.get(cls, ()):
            return f"{cls}.{attr}"
        return None

    def _transitive_locks(self) -> dict[int, set[str]]:
        """Per-function transitive lock-acquisition sets by FIXPOINT over
        the whole call graph — exact under cycles. (A recursive memo
        with a cycle cut is unsound: a callee memoized while an ancestor
        is on the stack caches an incomplete set, making edge detection
        depend on call-site order.)"""
        if self._trans is not None:
            return self._trans
        nodes = [(cls, fn) for lst in self.methods.values()
                 for cls, fn in lst]
        direct: dict[int, set[str]] = {}
        callees: dict[int, set[int]] = {}
        for cls, fn in nodes:
            d: set[str] = set()
            cs: set[int] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for attr in _with_locks(node):
                        lid = self.lock_id(cls, attr)
                        if lid:
                            d.add(lid)
                elif isinstance(node, ast.Call):
                    f = node.func
                    name = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else None)
                    if name is None or name in _MUTATORS \
                            or name == "__init__":
                        continue
                    for _, callee_fn in self.resolve(cls, node):
                        cs.add(id(callee_fn))
            direct[id(fn)] = d
            callees[id(fn)] = cs
        trans = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for k, cs in callees.items():
                for c in cs:
                    if c in trans and not trans[c] <= trans[k]:
                        trans[k] |= trans[c]
                        changed = True
        self._trans = trans
        return trans

    def acquired_locks(self, cls: str, fn: ast.AST) -> frozenset[str]:
        """Locks this function (transitively) acquires — the callee side
        of a holds→acquires edge."""
        return frozenset(self._transitive_locks().get(id(fn), ()))

    def edges(self) -> dict[tuple[str, str], str]:
        """(held, acquired) -> 'Class.method:lineno' witness."""
        out: dict[tuple[str, str], str] = {}
        for sf in self.files:
            if sf.tree is None:
                continue
            for cls_node in sf.tree.body:
                if not isinstance(cls_node, ast.ClassDef):
                    continue
                cls = cls_node.name
                for fn in cls_node.body:
                    if not isinstance(fn, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                        continue
                    for node in ast.walk(fn):
                        if not isinstance(node, ast.With):
                            continue
                        held = [self.lock_id(cls, a)
                                for a in _with_locks(node)]
                        held = [h for h in held if h]
                        if not held:
                            continue
                        inner: set[str] = set()
                        for sub in node.body:
                            for call in ast.walk(sub):
                                if isinstance(call, ast.With):
                                    for attr in _with_locks(call):
                                        lid = self.lock_id(cls, attr)
                                        if lid:
                                            inner.add(lid)
                                elif isinstance(call, ast.Call):
                                    f = call.func
                                    name = f.attr if isinstance(
                                        f, ast.Attribute) else (
                                        f.id if isinstance(f, ast.Name)
                                        else None)
                                    if name is None or name in _MUTATORS:
                                        continue
                                    for ccls, cfn in self.resolve(
                                            cls, call):
                                        inner |= self.acquired_locks(
                                            ccls, cfn)
                        for h in held:
                            for a in inner:
                                out.setdefault(
                                    (h, a),
                                    f"{sf.rel}:{node.lineno} "
                                    f"({cls}.{fn.name})")
        return out


def _find_cycle(edges: dict[tuple[str, str], str]) -> list[str] | None:
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: list[str] = []

    def dfs(n: str) -> list[str] | None:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(graph[n]):
            if color[m] == GRAY:
                return stack[stack.index(m):] + [m]
            if color[m] == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


@rule("lock-order",
      "the runtime lock-acquisition-order graph is acyclic "
      "(no self-edges: every Lock here is non-reentrant)",
      suppressible=False)
def check_lock_order(project: Project):
    findings: list[Finding] = []
    g = _LockGraph(project)
    edges = g.edges()
    # self-edges first: taking the same class's non-reentrant lock while
    # holding it deadlocks outright
    for (a, b), where in sorted(edges.items()):
        if a == b:
            findings.append(Finding(
                "lock-order", where.split(":")[0],
                int(where.split(":")[1].split()[0]),
                f"holding {a} while a reachable callee re-acquires {a} "
                f"(non-reentrant threading.Lock) — self-deadlock"))
    acyclic_edges = {k: v for k, v in edges.items() if k[0] != k[1]}
    cyc = _find_cycle(acyclic_edges)
    if cyc:
        findings.append(Finding(
            "lock-order", RUNTIME_DIR, 0,
            f"lock-acquisition-order cycle: {' -> '.join(cyc)} "
            f"(witnesses: "
            + "; ".join(acyclic_edges[(cyc[i], cyc[i + 1])]
                        for i in range(len(cyc) - 1)
                        if (cyc[i], cyc[i + 1]) in acyclic_edges) + ")"))
    n_locks = sum(len(v) for v in g.class_locks.values())
    return findings, (f"{n_locks} locks, {len(edges)} ordered "
                      f"acquisition edges, no cycles")
