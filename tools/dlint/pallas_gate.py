"""pallas-gate — every Pallas kernel module routes mode selection through
the ONE shared gate.

The PR8 review finding ("one shared gate") promoted to a machine-checked
invariant: ``ops/quant_matmul.pallas_mode_gate`` is the single place the
``DLLAMA_TPU_QUANT_KERNEL`` env knob turns into a kernel choice, so the
col-split tp path, the overlapped merge, the wire pricing, and the ragged
paged attention entry can never drift from what ``linear()`` dispatches
— and ``DLLAMA_TPU_QUANT_KERNEL=xla`` stays a working kill switch for
EVERY Pallas kernel in the tree.

**Invariant:** a module under ``dllama_tpu/`` containing a
``pl.pallas_call`` site must reference ``pallas_mode_gate`` (its own
dispatch consults the shared gate), except the two modules that predate
or define the gate: ``ops/quant_matmul.py`` (defines it) and
``ops/flash_attention.py`` (its enablement is the attention-impl knob,
``cfg.attn_impl``, selected by the model layer — a per-config choice,
not the env gate). A new kernel module that invents its own ad-hoc env
knob or hardcodes enablement fires this rule at each ``pallas_call``
line.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, rule

RULE = "pallas-gate"

# modules exempt from the reference requirement: the gate's own home and
# the pre-gate attention kernel (enabled via cfg.attn_impl, see docstring)
_EXEMPT = (
    "dllama_tpu/ops/quant_matmul.py",
    "dllama_tpu/ops/flash_attention.py",
)


def _pallas_call_lines(tree: ast.AST) -> list[int]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "pallas_call") \
                    or (isinstance(f, ast.Name) and f.id == "pallas_call"):
                out.append(node.lineno)
    return sorted(out)


def _calls_gate(tree: ast.AST) -> bool:
    """True when the module CALLS pallas_mode_gate somewhere (directly or
    as an attribute) — a bare import or name reference does not count, so
    an unused ``from .quant_matmul import pallas_mode_gate`` can't
    satisfy the invariant. Granularity is deliberately module-level: the
    ``pallas_call`` site and the gate consult legitimately live in
    different functions of one kernel module (the private ``_call`` vs
    the public dispatch entry), so per-function checking would
    false-positive on every compliant module."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr == "pallas_mode_gate") \
                or (isinstance(f, ast.Name) and f.id == "pallas_mode_gate"):
            return True
    return False


@rule(RULE, "pallas_call sites route mode selection through "
            "quant_matmul.pallas_mode_gate")
def check(project: Project):
    files = project.walk("dllama_tpu")
    findings = list(project.parse_failures(files, RULE))
    n_sites = 0
    n_modules = 0
    for sf in files:
        if sf.tree is None:
            continue
        lines = _pallas_call_lines(sf.tree)
        if not lines:
            continue
        n_modules += 1
        n_sites += len(lines)
        if sf.rel.replace("\\", "/") in _EXEMPT:
            continue
        if _calls_gate(sf.tree):
            continue
        for lineno in lines:
            findings.append(Finding(
                RULE, sf.rel, lineno,
                "pl.pallas_call in a module that never consults "
                "quant_matmul.pallas_mode_gate — kernel mode selection "
                "must route through the ONE shared gate (so "
                "DLLAMA_TPU_QUANT_KERNEL=xla stays a working kill switch "
                "and modes can't drift per kernel); call it from this "
                "module's dispatch gate, or add the module to the "
                "documented exempt list in tools/dlint/pallas_gate.py"))
    return findings, (f"{n_sites} pallas_call site(s) across {n_modules} "
                      f"module(s) gate-routed or exempt")
