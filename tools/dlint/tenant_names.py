"""Tenant decision-reason rule.

The tenant observatory's admission decisions (defer / shed / requeue /
preempt flight notes in ``runtime/serving.py``, ``note_shed`` calls in
``runtime/serving.py`` and ``serve/router.py``) are only queryable if
every decision names a reason from ONE closed vocabulary
(``dllama_tpu.runtime.tenancy.ADMIT_REASONS``). This rule keeps that
vocabulary closed in BOTH directions — every emit site names a declared
reason, every declared reason has a live emit site and a doc line — and
holds the ``dllama_tenant_*`` metric family closed-world between
``telemetry.SPECS`` and PERF.md (the tenant-scoped twin of the
metrics-names rule, so a renamed tenant metric cannot strand its docs).
A misspelled reason must fail lint, not silently never match a
postmortem query. Importing only tenancy/telemetry keeps this runnable
without jax.
"""

from __future__ import annotations

import re
import sys

from .core import REPO, Finding, Project, rule

# the grammar each ADMIT_REASONS member must satisfy
GRAMMAR_RE = re.compile(r"^[a-z][a-z0-9_]{0,31}$")

TENANCY = "dllama_tpu/runtime/tenancy.py"
T = "dllama_tpu/runtime/telemetry.py"
# the files allowed (and required) to emit admission decisions
EMIT_FILES = ("dllama_tpu/runtime/serving.py",
              "dllama_tpu/serve/router.py")
DOC_FILES = ("PERF.md",)

# an admission-decision flight note: the event name is one of the four
# decision verbs and a reason= kwarg follows inside the same call (the
# gap excludes ')' so the match cannot leak into a neighboring call).
# timeout/cancel notes carry their own lifecycle reasons (queued /
# admitting / in_flight) and are deliberately out of scope.
NOTE_RE = re.compile(
    r'\.note\(\s*"(?:defer|shed|requeue|preempt)"[^)]{0,200}?'
    r'reason="([a-z_]+)"', re.DOTALL)
# a per-tenant shed attribution (TenantRegistry.note_shed): the second
# positional argument is the reason literal
SHED_RE = re.compile(r'\.note_shed\(\s*[^,()]+,\s*"([a-z_]+)"')

TENANT_METRIC_RE = re.compile(r"\b(dllama_tenant_[a-z0-9_]+)")


def _load_vocab():
    sys.path.insert(0, str(REPO))
    try:
        from dllama_tpu.runtime.telemetry import SPECS
        from dllama_tpu.runtime.tenancy import ADMIT_REASONS
    finally:
        sys.path.pop(0)
    return ADMIT_REASONS, SPECS


def check(project: Project, vocab=None) -> tuple[list[Finding], str]:
    """``vocab`` — an ``(ADMIT_REASONS, SPECS)`` pair — is injectable
    for fixture self-tests; defaults to the repo's live vocabulary."""
    reasons, specs = vocab if vocab is not None else _load_vocab()
    findings: list[Finding] = []

    def f(path, msg, lineno=0):
        findings.append(Finding("tenant-reasons", path, lineno, msg))

    for name in reasons:
        if not GRAMMAR_RE.match(name):
            f(TENANCY, f"admission reason {name!r} violates the grammar "
                       f"([a-z][a-z0-9_]*)")

    # every reason carries its own doc line in the ADMIT_REASONS comment
    # block (the ``* ``reason`` — ...`` convention): a reason with no
    # prose is a label nobody can interpret in a postmortem
    sf = project.file(TENANCY)
    tenancy_text = sf.text if sf is not None else ""
    for name in reasons:
        if f"``{name}``" not in tenancy_text:
            f(TENANCY, f"admission reason {name!r} has no doc line in "
                       f"the ADMIT_REASONS comment block")

    # emit sites: both directions against the declared vocabulary
    emitted: dict[str, int] = {}
    for rel in EMIT_FILES:
        sf = project.file(rel)
        text = sf.text if sf is not None else ""
        for m in list(NOTE_RE.finditer(text)) + list(SHED_RE.finditer(text)):
            reason = m.group(1)
            lineno = text.count("\n", 0, m.start()) + 1
            emitted[reason] = emitted.get(reason, 0) + 1
            if reason not in reasons:
                f(rel, f"admission decision names reason {reason!r}, "
                       f"which is not in tenancy.ADMIT_REASONS",
                  lineno)
    for name in reasons:
        if name not in emitted:
            f(TENANCY, f"admission reason {name!r} has no emit site in "
                       f"{' or '.join(EMIT_FILES)} (dead vocabulary "
                       f"entry — remove it or wire the decision)")

    # the dllama_tenant_* metric family: registered names documented,
    # documented names registered, and reasons spelled out in PERF.md
    tenant_metrics = sorted(n for n in specs
                            if n.startswith("dllama_tenant_"))
    if not tenant_metrics:
        f(T, "no dllama_tenant_* metrics registered in telemetry.SPECS "
             "(the tenant observatory family is missing)")
    for rel in DOC_FILES:
        sf = project.file(rel)
        text = sf.text if sf is not None else ""
        for name in tenant_metrics:
            if name not in text:
                f(rel, f"tenant metric {name} is not documented in {rel}")
        for name in sorted(set(TENANT_METRIC_RE.findall(text))):
            base = re.sub(r"_(bucket|sum|count)$", "", name)
            if name not in specs and base not in specs:
                f(rel, f"{rel} mentions {name!r} but no such metric is "
                       f"registered in telemetry.SPECS (stale doc or "
                       f"typo)")
        for name in reasons:
            if name not in text:
                f(rel, f"admission reason {name!r} is not documented "
                       f"in {rel} (the shed/defer taxonomy must be "
                       f"operator-visible)")

    return findings, (f"{len(reasons)} admission reasons across "
                      f"{sum(emitted.values())} emit sites + "
                      f"{len(tenant_metrics)} dllama_tenant_* metrics: "
                      f"vocabulary, emit sites, and docs all consistent")


rule("tenant-reasons",
     "every tenant admission decision (defer/shed/requeue/preempt) "
     "names a reason from tenancy.ADMIT_REASONS, every reason has a "
     "live emit site and docs, and the dllama_tenant_* family is "
     "closed-world vs telemetry.SPECS and PERF.md")(check)
