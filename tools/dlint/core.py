"""dlint core — the shared machinery every rule module rides on.

What the six pre-dlint scanners each re-implemented (~650 LoC of copied
file walking, comment stripping, and ❌/✅ printing) lives here exactly
once:

* :class:`SourceFile` — one parsed file: raw text, split lines, a cached
  ``ast`` tree, comment/docstring-stripped *code lines* (for text-regex
  rules that must not fire on prose), and the per-line suppression table
  parsed from ``# dlint: disable=RULE[,RULE...]`` comments.
* :class:`Project` — the file walker: rooted at the repo, caches
  :class:`SourceFile` objects, skips ``__pycache__``/non-UTF-8 noise.
* :class:`Finding` — one ``file:line: message`` diagnostic, tagged with
  the rule id that produced it.
* :func:`rule` — the visitor/rule registry. A rule is a function
  ``(project) -> (findings, summary)``: the findings it would report and
  a one-line ✅ summary for the clean case.
* :func:`run_rules` — the reporter: applies suppressions (a finding on a
  line carrying ``# dlint: disable=<its rule>`` is counted, not
  printed), prints ❌ per finding / ✅ per clean rule, and can emit the
  one-line JSON summary CI consumes.

No jax, no package imports at module scope — ``python -m tools.dlint``
must run anywhere ``make lint`` runs, including bare CI runners before
the native build.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
import sys
from dataclasses import dataclass, field
from typing import Callable, Iterable

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

# -- suppressions -------------------------------------------------------------

# `# dlint: disable=rule-a,rule-b` — suppresses findings of those rules ON
# THAT LINE (one comment, one line, exactly the findings anchored there).
_DISABLE_RE = re.compile(r"#\s*dlint:\s*disable=([a-z0-9_,-]+)")

_QUOTES = ('"""', "'''")
_INLINE_TRIPLE = re.compile(r"(\"\"\"|''').*?\1")


@dataclass(frozen=True)
class Finding:
    """One diagnostic: ``rule`` id, repo-relative ``path``, 1-based
    ``lineno`` (0 = whole-file/doc finding), human message."""

    rule: str
    path: str
    lineno: int
    message: str

    def __str__(self) -> str:
        loc = f"{self.path}:{self.lineno}" if self.lineno else self.path
        return f"{loc}: [{self.rule}] {self.message}"


class SourceFile:
    """One file's parsed views, computed lazily and cached."""

    def __init__(self, path: pathlib.Path, root: pathlib.Path):
        self.path = path
        self.rel = str(path.relative_to(root))
        self._text: str | None = None
        self._tree: ast.AST | None = None
        self._code_lines: list[tuple[int, str]] | None = None
        self._suppress: dict[int, set[str]] | None = None
        self.parse_error: str | None = None

    @property
    def text(self) -> str:
        if self._text is None:
            raw = self.path.read_bytes()
            try:
                self._text = raw.decode("utf-8")
            except UnicodeDecodeError as e:
                # never crash a rule on one undecodable file: text-regex
                # rules run on the replaced text; AST rules see the file
                # via parse_failures (tree stays None, parse_error set)
                self.parse_error = f"non-UTF-8 source: {e}"
                self._text = raw.decode("utf-8", errors="replace")
        return self._text

    @property
    def lines(self) -> list[str]:
        return self.text.splitlines()

    @property
    def tree(self) -> ast.AST | None:
        """The parsed AST, or None (with ``parse_error`` set) when the
        file does not parse — rules report unparseable files once via
        :meth:`Project.parse_failures`, not per rule."""
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=str(self.path))
            except SyntaxError as e:
                self.parse_error = str(e)
        return self._tree

    def code_lines(self) -> list[tuple[int, str]]:
        """``(lineno, line)`` pairs with ``#`` comments stripped and
        docstring/triple-quoted bodies skipped — prose may legitimately
        NAME a banned spelling; only executable references are
        violations. Crude triple-quote tracking (a line with an odd count
        of the same quote toggles string state) matches this repo's
        style, same as the historical scanners."""
        if self._code_lines is not None:
            return self._code_lines
        out: list[tuple[int, str]] = []
        in_str: str | None = None
        for lineno, line in enumerate(self.text.splitlines(), 1):
            if in_str is not None:
                if line.count(in_str) % 2 == 1:
                    in_str = None
                continue
            # whole triple-quoted strings on ONE line drop out entirely
            # (one-line docstrings may name banned spellings too)
            line = _INLINE_TRIPLE.sub('""', line)
            opened = [q for q in _QUOTES if line.count(q) % 2 == 1]
            if opened:
                out.append((lineno, line.split(opened[0], 1)[0]))
                in_str = opened[0]
                continue
            out.append((lineno, line.split("#", 1)[0]))
        self._code_lines = out
        return out

    def suppressions(self) -> dict[int, set[str]]:
        """lineno -> rule ids disabled on that line."""
        if self._suppress is None:
            self._suppress = {}
            for lineno, line in enumerate(self.lines, 1):
                m = _DISABLE_RE.search(line)
                if m:
                    self._suppress[lineno] = {
                        r.strip() for r in m.group(1).split(",") if r.strip()}
        return self._suppress

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        return rule_id in self.suppressions().get(lineno, ())


class Project:
    """The walker: repo root + cached per-file source models."""

    def __init__(self, root: pathlib.Path | str = REPO):
        self.root = pathlib.Path(root).resolve()
        self._files: dict[pathlib.Path, SourceFile] = {}

    def file(self, rel: str | pathlib.Path) -> SourceFile | None:
        """One file by repo-relative path, or None if it doesn't exist."""
        path = (self.root / rel).resolve()
        if not path.is_file():
            return None
        if path not in self._files:
            self._files[path] = SourceFile(path, self.root)
        return self._files[path]

    def walk(self, *rel_dirs: str) -> list[SourceFile]:
        """Every ``*.py`` under the given repo-relative dirs (sorted,
        ``__pycache__`` skipped). Missing dirs contribute nothing."""
        out: list[SourceFile] = []
        for d in rel_dirs:
            base = self.root / d
            if not base.is_dir():
                continue
            for py in sorted(base.rglob("*.py")):
                if "__pycache__" in py.parts:
                    continue
                out.append(self.file(py.relative_to(self.root)))  # type: ignore[arg-type]
        return out

    def parse_failures(self, files: Iterable[SourceFile],
                       rule_id: str) -> list[Finding]:
        """Findings for files that are not clean parseable UTF-8 Python
        (forces decode + ``tree``). A non-UTF-8 file whose replaced text
        still parses is reported too — rules analyzed a lossy view of
        it."""
        out = []
        for sf in files:
            sf.text  # force the decode so non-UTF-8 is recorded
            if sf.tree is None or sf.parse_error:
                out.append(Finding(rule_id, sf.rel, 0,
                                   f"unparseable: {sf.parse_error}"))
        return out


# -- rule registry ------------------------------------------------------------

@dataclass
class Rule:
    name: str
    doc: str
    fn: Callable[[Project], tuple[list[Finding], str]]
    # suppressible=False for rules whose findings live in non-Python files
    # (docs, registries) where a disable comment has nowhere to sit
    suppressible: bool = True


_RULES: dict[str, Rule] = {}


def rule(name: str, doc: str, *, suppressible: bool = True):
    """Register ``fn(project) -> (findings, clean_summary)`` as a rule."""

    def deco(fn):
        _RULES[name] = Rule(name=name, doc=doc, fn=fn,
                            suppressible=suppressible)
        return fn

    return deco


def load_rule_modules() -> None:
    """Import every rule module so its ``@rule`` registrations run."""
    from . import (  # noqa: F401
        eval_names,
        exception_hygiene,
        failpoint_sites,
        failure_taxonomy,
        metrics_names,
        pallas_gate,
        route_labels,
        slo_names,
        span_phases,
        tenant_names,
        thread_ownership,
        trace_safety,
    )


def all_rules() -> dict[str, Rule]:
    load_rule_modules()
    return dict(_RULES)


def get_rule(name: str) -> Rule:
    rules = all_rules()
    if name not in rules:
        known = ", ".join(sorted(rules))
        raise SystemExit(f"dlint: unknown rule {name!r} (known: {known})")
    return rules[name]


# -- runner / reporter --------------------------------------------------------

@dataclass
class RuleResult:
    rule: Rule
    findings: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    summary: str = ""
    error: str | None = None


def run_rule(r: Rule, project: Project) -> RuleResult:
    """Run one rule and split its findings into active vs suppressed."""
    res = RuleResult(rule=r)
    try:
        findings, summary = r.fn(project)
    except Exception as e:
        res.error = f"{type(e).__name__}: {e}"
        return res
    res.summary = summary
    for f in findings:
        sf = project.file(f.path) if r.suppressible and f.lineno else None
        if sf is not None and sf.suppressed(f.rule, f.lineno):
            res.suppressed.append(f)
        else:
            res.findings.append(f)
    return res


def run_rules(project: Project | None = None, *,
              only: Iterable[str] | None = None,
              json_out: bool = False,
              stream=None) -> int:
    """Run rules and report; returns the process exit code (0 = clean)."""
    project = project or Project()
    stream = stream or sys.stdout
    rules = all_rules()
    names = list(only) if only else sorted(rules)
    for n in names:
        if n not in rules:
            get_rule(n)  # raises with the known-rule list
    results = [run_rule(rules[n], project) for n in names]

    n_findings = sum(len(r.findings) for r in results)
    n_suppressed = sum(len(r.suppressed) for r in results)
    n_errors = sum(1 for r in results if r.error)
    ok = n_findings == 0 and n_errors == 0

    if json_out:
        payload = {
            "ok": ok,
            "rules": len(results),
            "findings": n_findings,
            "suppressed": n_suppressed,
            "per_rule": {
                r.rule.name: {
                    "findings": len(r.findings),
                    "suppressed": len(r.suppressed),
                    **({"error": r.error} if r.error else {}),
                } for r in results
            },
        }
        print(json.dumps(payload, sort_keys=True), file=stream)
        return 0 if ok else 1

    for r in results:
        if r.error:
            print(f"❌ [{r.rule.name}] rule crashed: {r.error}",
                  file=sys.stderr)
            continue
        for f in r.findings:
            print(f"❌ {f}", file=sys.stderr)
        if not r.findings:
            sup = f" ({len(r.suppressed)} suppressed)" if r.suppressed else ""
            print(f"✅ [{r.rule.name}] {r.summary or r.rule.doc}{sup}",
                  file=stream)
    if not ok:
        print(f"dlint: {n_findings} finding(s) across "
              f"{sum(1 for r in results if r.findings or r.error)} rule(s)",
              file=sys.stderr)
    return 0 if ok else 1
