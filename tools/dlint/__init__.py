"""dlint — the repo's unified AST static-analysis framework.

One file walker, one comment/docstring-aware source model, one visitor
registry, one ``file:line`` finding reporter with ``# dlint: disable=RULE``
suppressions — and every repo invariant as a rule module on top:

* :mod:`tools.dlint.trace_safety` — closed-world jit entry, tracer-hazard
  detection inside traced function bodies, guarded-twin completeness.
* :mod:`tools.dlint.thread_ownership` — declared thread ownership
  (``# dlint: owner=...``), monitor-vs-loop call-graph checking,
  lock-discipline (``# dlint: guarded-by=...``) and lock-order cycles.
* the six historical ``tools/check_*.py`` scanners, consolidated as rule
  modules (:mod:`tools.dlint.metrics_names`, ``exception_hygiene``,
  ``route_labels``, ``failpoint_sites``, ``span_phases``,
  ``shard_map_shim``) — each old CLI entry point survives as a thin
  wrapper.
* :mod:`tools.dlint.slo_names` — the SLO observatory's objective
  vocabulary (``runtime/slo.OBJECTIVES``) closed-world across the cli
  grammar, gauges, bench output, and docs.

Run everything: ``python -m tools.dlint`` (repo-clean exit 0); one rule:
``--only RULE``; machine-readable: ``--json``. The invariant catalog
(what each rule enforces, the review finding that motivated it, how to
suppress) lives in ``LINTS.md``.
"""

from .core import (  # noqa: F401
    Finding,
    Project,
    Rule,
    all_rules,
    get_rule,
    load_rule_modules,
    rule,
    run_rules,
)
