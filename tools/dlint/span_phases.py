"""Span-phase rule (migrated from ``tools/check_span_phases.py``).

The span ring's phase vocabulary (``runtime/telemetry.PHASES``) is an
operator contract: every SpanTracer call site emits a CONSTANT phase
from the vocabulary, every member is emitted somewhere, and both the
telemetry docstring and PERF.md document it. The router tier's span
ring (``serve/router.py RouterSpanRing.emit_span``) carries the same
contract against ``telemetry.ROUTER_PHASES``.
"""

from __future__ import annotations

import ast
import sys

from .core import REPO, Finding, Project, rule

PKG = "dllama_tpu"


def _load_phases():
    sys.path.insert(0, str(REPO))
    try:
        from dllama_tpu.runtime.telemetry import PHASES, ROUTER_PHASES
    finally:
        sys.path.pop(0)
    return PHASES, ROUTER_PHASES


def _is_tracer_emit(node: ast.Call) -> bool:
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "emit"
            and isinstance(f.value, ast.Call)):
        return False
    inner = f.value.func
    return (isinstance(inner, ast.Name) and inner.id == "tracer") or \
        (isinstance(inner, ast.Attribute) and inner.attr == "tracer")


def _is_router_emit(node: ast.Call) -> bool:
    """``<anything>.emit_span(...)`` — the RouterSpanRing method name is
    unique in the tree, so matching the attribute is enough."""
    return isinstance(node.func, ast.Attribute) \
        and node.func.attr == "emit_span"


def check(project: Project, phases=None) -> tuple[list[Finding], str]:
    phases, router_phases = (phases if phases is not None
                             else _load_phases())
    findings: list[Finding] = []
    sites: dict[str, list[tuple[str, int]]] = {}
    r_sites: dict[str, list[tuple[str, int]]] = {}

    for sf in project.walk(PKG):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_tracer_emit(node):
                into, what = sites, "tracer().emit"
            elif _is_router_emit(node):
                into, what = r_sites, "emit_span"
            else:
                continue
            if len(node.args) < 2 or not (
                    isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                findings.append(Finding(
                    "span-phases", sf.rel, node.lineno,
                    f"{what} phase argument is not a string "
                    f"constant — the closed-world vocabulary cannot be "
                    f"checked"))
                continue
            into.setdefault(node.args[1].value, []).append(
                (sf.rel, node.lineno))

    T = f"{PKG}/runtime/telemetry.py"
    for vocab_name, vocab, found in (
            ("telemetry.PHASES", phases, sites),
            ("telemetry.ROUTER_PHASES", router_phases, r_sites)):
        for phase, where in sorted(found.items()):
            if phase not in vocab:
                findings.append(Finding(
                    "span-phases", where[0][0], where[0][1],
                    f"emits span phase {phase!r} which is not in "
                    f"{vocab_name} (typo, or add it to the documented "
                    f"vocabulary)"))
        for phase in vocab:
            if phase not in found:
                findings.append(Finding(
                    "span-phases", T, 0,
                    f"{vocab_name} documents {phase!r} but no call "
                    f"site emits it (dead vocabulary)"))

    tsf = project.file(T)
    telemetry_src = tsf.text if tsf is not None else ""
    psf = project.file("PERF.md")
    perf = psf.text if psf is not None else ""
    for phase in (*phases, *router_phases):
        if f"``{phase}``" not in telemetry_src:
            findings.append(Finding(
                "span-phases", T, 0,
                f"phase {phase!r} is not described in the telemetry.py "
                f"vocabulary docstring"))
        if phase not in perf:
            findings.append(Finding(
                "span-phases", "PERF.md", 0,
                f"phase {phase!r} is not documented in PERF.md"))

    n_sites = sum(len(w) for w in sites.values()) \
        + sum(len(w) for w in r_sites.values())
    return findings, (f"{len(phases)} span + {len(router_phases)} router "
                      f"phases: {n_sites} call sites, vocabulary + "
                      f"telemetry docstring + PERF.md all consistent")


rule("span-phases",
     "every SpanTracer phase literal is in telemetry.PHASES; the "
     "vocabulary is emitted and documented")(check)
