#!/usr/bin/env python
"""Generate the committed production-shape BPE tokenizer fixture.

The reference gates its tokenizer DEV_TESTS on a real downloaded Llama-3
tokenizer (src/tokenizer-test.cpp:44-120). This environment has zero egress,
so the fixture is the next-best thing: a byte-level BPE vocabulary TRAINED
here (deterministically) on an embedded multilingual corpus — thousands of
multi-byte pieces with genuine merge ranks learned from data, laid out
exactly the way convert/tokenizers.py lays out real HF vocabs (256 byte
-fallback entries + merges in rank order, scores = -id, specials after the
regular vocab).

Outputs (committed):
  tests/goldens/fixture_bpe.t        the tokenizer file
  tests/goldens/fixture_bpe.json     encode goldens for the sample strings

Rerun ``python tools/make_tokenizer_fixture.py`` to regenerate; the output
is byte-stable (pure-deterministic training, no RNG).
"""

from __future__ import annotations

import json
import os
import sys
from collections import Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_MERGES = 2400

# An embedded multilingual corpus: English prose, European accents, Greek,
# Cyrillic, CJK, emoji, code, numbers — enough pair statistics for real
# multi-byte merges. (Public-domain snippets + filler, intentionally bland.)
CORPUS = (
    "The quick brown fox jumps over the lazy dog. "
    "It was the best of times, it was the worst of times, it was the age of "
    "wisdom, it was the age of foolishness, it was the epoch of belief. "
    "To be, or not to be, that is the question: whether 'tis nobler in the "
    "mind to suffer the slings and arrows of outrageous fortune. "
    "All happy families are alike; each unhappy family is unhappy in its own "
    "way. Call me Ishmael. Some years ago, never mind how long precisely. "
    "We the People of the United States, in Order to form a more perfect "
    "Union, establish Justice, insure domestic Tranquility. "
    "def tokenize(text):\n    return [t for t in text.split() if t]\n"
    "for i in range(100):\n    print(f\"token {i}: {vocab[i]}\")\n"
    "The model processes 1024 tokens per batch at 3.14 tokens/second. "
    "Résumé naïve café déjà vu — l'été à Zürich coûte 42 €. "
    "Der schnelle braune Fuchs springt über den faulen Hund. "
    "El rápido zorro marrón salta sobre el perro perezoso. "
    "Ο γρήγορος καφές αλεπού πηδά πάνω από το τεμπέλικο σκυλί. "
    "Быстрая коричневая лиса прыгает через ленивую собаку. "
    "素早い茶色の狐はのろまな犬を飛び越える。日本語のテキストです。"
    "敏捷的棕色狐狸跳过懒狗。中文文本示例。"
    "빠른 갈색 여우가 게으른 개를 뛰어넘는다. "
    "🦊🐕 emoji test 🎉🚀 done. "
)

# synthetic long tail: varied word/number/punctuation contexts so pair
# statistics stay rich enough for thousands of merges (pure repetition
# starves the pair counts after a few hundred)
_WORDS = ("model tensor shard device batch token layer cache prefill decode "
          "attention expert router pipeline mesh collective kernel scale "
          "memory stream weight logits sample greedy verify draft accept "
          "серверу обучение модель 模型 训练 データ 処理 변환 처리").split()
_TAIL = []
for i in range(700):
    w1 = _WORDS[i % len(_WORDS)]
    w2 = _WORDS[(i * 7 + 3) % len(_WORDS)]
    _TAIL.append(f"The {w1} writes {i} {w2}s, then {w1}-{w2} #{i % 97}. ")
CORPUS = (CORPUS + "".join(_TAIL)) * 2


MAX_PIECE_LEN = 16  # production vocabs keep pieces short (Llama-3 ~max 128)


def train_bpe(data: bytes, n_merges: int) -> list[bytes]:
    """Classic BPE: repeatedly merge the most frequent adjacent pair.
    Ties break on the lexicographically smaller pair — fully deterministic.
    Pieces are capped at MAX_PIECE_LEN bytes (unbounded chaining on a small
    corpus merges whole sentences into single tokens, which no production
    vocab does). Returns learned pieces in merge (rank) order."""
    seq: list[bytes] = [bytes([b]) for b in data]
    merges: list[bytes] = []
    for _ in range(n_merges):
        counts: Counter = Counter(zip(seq, seq[1:]))
        if not counts:
            break
        best, freq = None, 0
        for pair, c in counts.items():
            if len(pair[0]) + len(pair[1]) > MAX_PIECE_LEN:
                continue
            if c > freq or (c == freq and best is not None
                            and pair < best):
                best, freq = pair, c
        if best is None or freq < 2:
            break
        merged = best[0] + best[1]
        merges.append(merged)
        out: list[bytes] = []
        i = 0
        while i < len(seq):
            if (i + 1 < len(seq) and seq[i] == best[0]
                    and seq[i + 1] == best[1]):
                out.append(merged)
                i += 2
            else:
                out.append(seq[i])
                i += 1
        seq = out
    return merges


SAMPLES = [
    "hello world",
    "The quick brown fox jumps over the lazy dog.",
    "Résumé naïve café — déjà vu à Zürich",
    "Быстрая лиса и 素早い狐 together",
    "🦊 emoji 🎉 mix with ASCII",
    "def tokenize(text):\n    return text.split()",
    "a",
    "    leading spaces and trailing   ",
    "ΑΒΓαβγ mixed Ελληνικά",
    "<|start_header_id|>user<|end_header_id|>\n\nhello<|eot_id|>",
]


def main() -> None:
    from dllama_tpu.formats import tfile
    from dllama_tpu.tokenizer.bpe import Tokenizer

    corpus = CORPUS.encode("utf-8")
    merges = train_bpe(corpus, N_MERGES)
    multi_byte = sum(1 for m in merges if len(m) >= 2 and any(b >= 0x80 for b in m))
    print(f"trained {len(merges)} merges ({multi_byte} contain non-ASCII bytes)")

    # layout mirrors convert/tokenizers.py resolve_hf_vocab + llama3 specials:
    # byte fallback first, merges in rank order, scores=-id, specials after
    vocab: list[bytes] = [bytes([b]) for b in range(256)] + merges
    scores = [-float(i) for i in range(len(vocab))]
    bos_id = len(vocab)
    specials = [b"<s>", b"</s>", b"<|start_header_id|>", b"<|end_header_id|>",
                b"<|eot_id|>"]
    vocab += specials
    scores += [0.0] * len(specials)

    data = tfile.TokenizerData(
        vocab=vocab, scores=scores, bos_id=bos_id, add_bos=True,
        eos_token_ids=[bos_id + 1, bos_id + 4],  # </s> and <|eot_id|>
        chat_template=None,
        max_token_length=max(len(t) for t in vocab),
    )
    out_dir = os.path.join(REPO, "tests", "goldens")
    t_path = os.path.join(out_dir, "fixture_bpe.t")
    tfile.write_tfile(t_path, data)

    tok = Tokenizer.load(t_path)
    goldens = []
    for s in SAMPLES:
        ids = tok.encode(s, is_start=False)
        tok.reset_decoder()
        rt = "".join(p for t in ids if (p := tok.decode(t)) is not None)
        # EOS specials stream as None by design (the reference hides EOS
        # text); everything else must round-trip exactly
        expect = s
        for e in tok.eos_token_ids:
            expect = expect.replace(tok.vocab[e].decode(), "")
        assert rt == expect, (s, rt)
        goldens.append({"text": s, "ids": ids})
    stats = {
        "n_merges": len(merges), "vocab_size": len(vocab),
        "multi_byte_merges": multi_byte,
        "max_piece_len": max(len(m) for m in merges),
    }
    with open(os.path.join(out_dir, "fixture_bpe.json"), "w") as f:
        json.dump({"stats": stats, "goldens": goldens}, f, indent=1,
                  ensure_ascii=False)
    print(f"wrote {t_path} ({os.path.getsize(t_path)} bytes) "
          f"+ goldens; stats={stats}")


if __name__ == "__main__":
    main()
