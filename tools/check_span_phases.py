#!/usr/bin/env python
"""Span-phase lint (Makefile ``lint`` target).

The span ring's phase vocabulary (``runtime/telemetry.PHASES``) is an
operator contract: ``/debug/requests`` timelines, ``--trace-out`` JSONL
consumers, and the flight recorder's Chrome-trace export all key on the
phase strings, and PERF.md documents them. The contract is closed-world,
both directions — the same shape as ``check_metrics_names.py``:

1. every phase literal emitted at a SpanTracer call site
   (``telemetry.tracer().emit(rid, "<phase>", ...)``) in ``dllama_tpu/``
   is a member of ``PHASES`` (a typo'd phase silently fragments request
   timelines) — and every call site passes a CONSTANT phase, so the
   world stays closeable;
2. every ``PHASES`` member has at least one call site (a documented
   phase nobody emits is timeline coverage that quietly rotted);
3. every ``PHASES`` member is mentioned in the telemetry.py source (the
   docstring vocabulary) and in PERF.md (the operator docs).

AST-based; importing only the telemetry module keeps this runnable
without jax.
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "dllama_tpu"
sys.path.insert(0, str(REPO))

from dllama_tpu.runtime.telemetry import PHASES  # noqa: E402


def _is_tracer_emit(node: ast.Call) -> bool:
    """Matches ``<...>tracer().emit(...)`` — the SpanTracer entry point
    (``telemetry.tracer().emit`` or a bare ``tracer().emit``)."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr == "emit"
            and isinstance(f.value, ast.Call)):
        return False
    inner = f.value.func
    return (isinstance(inner, ast.Name) and inner.id == "tracer") or \
        (isinstance(inner, ast.Attribute) and inner.attr == "tracer")


def emitted_phases() -> tuple[dict[str, list[str]], list[str]]:
    """phase -> call sites, plus errors for non-constant phase args."""
    sites: dict[str, list[str]] = {}
    errors: list[str] = []
    for py in sorted(PKG.rglob("*.py")):
        tree = ast.parse(py.read_text(encoding="utf-8"), filename=str(py))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_tracer_emit(node)):
                continue
            where = f"{py.relative_to(REPO)}:{node.lineno}"
            if len(node.args) < 2 or not (
                    isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                errors.append(f"{where}: tracer().emit phase argument is "
                              f"not a string constant — the closed-world "
                              f"vocabulary cannot be checked")
                continue
            sites.setdefault(node.args[1].value, []).append(where)
    return sites, errors


def main() -> int:
    sites, errors = emitted_phases()

    for phase, where in sorted(sites.items()):
        if phase not in PHASES:
            errors.append(f"{where[0]}: emits span phase {phase!r} which "
                          f"is not in telemetry.PHASES (typo, or add it "
                          f"to the documented vocabulary)")
    for phase in PHASES:
        if phase not in sites:
            errors.append(f"telemetry.PHASES documents {phase!r} but no "
                          f"tracer().emit call site emits it (dead "
                          f"vocabulary)")

    telemetry_src = (PKG / "runtime" / "telemetry.py").read_text(
        encoding="utf-8")
    perf = (REPO / "PERF.md").read_text(encoding="utf-8")
    for phase in PHASES:
        if f"``{phase}``" not in telemetry_src:
            errors.append(f"phase {phase!r} is not described in the "
                          f"telemetry.py vocabulary docstring")
        if phase not in perf:
            errors.append(f"phase {phase!r} is not documented in PERF.md")

    if errors:
        for e in errors:
            print(f"❌ {e}", file=sys.stderr)
        return 1
    n_sites = sum(len(w) for w in sites.values())
    print(f"✅ {len(PHASES)} span phases: {n_sites} call sites, vocabulary "
          f"+ telemetry docstring + PERF.md all consistent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
