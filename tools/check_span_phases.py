#!/usr/bin/env python
"""Span-phase lint: every SpanTracer phase literal is in telemetry.PHASES; the vocabulary is emitted and documented.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself now
lives on the shared dlint framework as the ``span-phases`` rule —
``python -m tools.dlint --only span-phases`` is the canonical entry point;
this script exists so historical CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["span-phases"])


if __name__ == "__main__":
    sys.exit(main())
