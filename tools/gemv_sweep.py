"""On-chip sweep of decode-GEMV kernel variants (honest slope timing).

The decode profile (tools/profile_decode.py) shows the Q40 quant matmul
streaming codes at ~114-130 GB/s effective against an 819 GB/s chip — the
dominant term in the 8.4x roofline gap.  This sweep times, for the hot
decode shapes, the production Pallas kernel at several (bn, bk) block
choices against: the decode-shaped FUSED dequant-GEMV kernel
(ops/quant_matmul._decode_kernel — one full-K pass per N stripe, dequant
in-register; the DLLAMA_TPU_QUANT_KERNEL=fused candidate), the XLA
dequant+dot fallback (f32- and bf16-stored scales), a dense bf16 matmul
(the no-quantization reference point), a raw s8xs8 MXU dot -> s32 (rate
bound for a w8a8 "turbo" mode), manually packed 4-bit codes unpacked on
the VPU (halved code HBM vs shift/mask cost), and multi-row activations
(M=8 verify / M=256 prefill-chunk shapes).

Timing methodology: the host->device round trip on the axon tunnel is
~67 ms and per-dispatch host enqueue is ~1 ms, so sub-millisecond kernels
cannot be timed by host-side rep loops at all.  Each variant instead runs
inside ONE dispatch as a ``lax.fori_loop`` whose carry perturbs the
activation every iteration (the weights — the bytes being measured — stay
loop-invariant, exactly like real decode; the carry dependency stops XLA
from hoisting the matmul).  Wall time is taken at two iteration counts and
the per-op cost is the SLOPE, which cancels the RTT and any fixed
dispatch/loop overhead.

Usage:  python tools/gemv_sweep.py [n_lo] [n_hi] [--json]

``--json`` prints ONE machine-readable JSON line (same contract as
``tools/profile_decode.py --json``): ``{"tool": "gemv_sweep",
"device_kind": ..., "rows": [{"shape", "label", "us", "gbps"}, ...]}`` —
scriptable kernel A/Bs, and ``tools/bench_compare.py`` diffs two sweep
lines ranking each variant's effective GB/s.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    args = [a for a in sys.argv[1:] if a != "--json"]
    as_json = "--json" in sys.argv[1:]
    n_lo = int(args[0]) if len(args) > 0 else 64
    n_hi = int(args[1]) if len(args) > 1 else 448
    import jax
    import jax.numpy as jnp

    from dllama_tpu.ops import quant_matmul as qm
    from dllama_tpu.ops.linear import QuantizedWeight, dequantize_weight

    rows: list = []

    def say(*a, **kw):
        if not as_json:
            print(*a, **kw)

    def fetch(x):
        jax.device_get(jnp.ravel(x)[0])

    key = jax.random.PRNGKey(0)

    def make_w(K, N):
        kc, ks = jax.random.split(jax.random.fold_in(key, N))
        codes = (jax.random.bits(kc, (K, N), jnp.uint8) & jnp.uint8(0x0F)
                 ).astype(jnp.int8) - 8
        scales = jax.random.uniform(ks, (K // 32, N), jnp.float32,
                                    minval=0.001, maxval=0.011)
        return QuantizedWeight(scales=scales, codes=codes)

    shape_label = [""]  # current "K=..,N=.." tag for the JSON rows

    def bench(label, op, x, *wargs, bytes_moved: int):
        """op(x, *wargs) -> y [1, N]; loop it on device, slope-time it."""

        @functools.partial(jax.jit, static_argnums=0)
        def looped(n, x, *wargs):
            def body(i, carry):
                x, acc = carry
                y = op(x, *wargs)
                acc = acc + jnp.sum(y, dtype=jnp.float32)
                # perturb the activation so no iteration is hoistable; the
                # scale keeps values finite over hundreds of iterations
                x = x * (1.0 + 1e-12 * acc).astype(x.dtype)
                return x, acc

            x, acc = jax.lax.fori_loop(0, n, body, (x, jnp.float32(0.0)))
            return acc

        row = {"shape": shape_label[0], "label": label, "us": None,
               "gbps": None}
        rows.append(row)
        try:
            times = {}
            for n in (n_lo, n_hi):
                fetch(looped(n, x, *wargs))  # compile + warm
                t0 = time.perf_counter()
                fetch(looped(n, x, *wargs))
                times[n] = time.perf_counter() - t0
            per_op = (times[n_hi] - times[n_lo]) / (n_hi - n_lo)
            if per_op <= 0:
                say(f"  {label:<28} not resolvable (slope <= 0)")
                row["error"] = "slope <= 0"
                return None
            gbps = bytes_moved / per_op / 1e9
            say(f"  {label:<28} {1e6 * per_op:9.1f} us  {gbps:7.1f} GB/s")
            row["us"] = round(1e6 * per_op, 2)
            row["gbps"] = round(gbps, 1)
            return per_op
        except Exception as e:  # noqa: BLE001
            say(f"  {label:<28} {type(e).__name__}: {str(e)[:70]}")
            row["error"] = f"{type(e).__name__}: {str(e)[:120]}"
            return None

    for K, N in ((2048, 8192), (4096, 14336), (2048, 128256)):
        w = make_w(K, N)
        x = jax.random.normal(jax.random.fold_in(key, K), (1, K), jnp.bfloat16)
        nbytes = K * N + (K // 32) * N * 4  # codes + f32 scales
        shape_label[0] = f"K={K},N={N}"
        say(f"\nGEMV [1,{K}] x [{K},{N}]  ({nbytes / 1e6:.0f} MB quant)",
            flush=True)

        for bn, bk in ((512, 512), (1024, 512), (2048, 512), (512, 1024),
                       (1024, 1024), (2048, 1024), (1024, 2048)):
            if N % bn or K % bk:
                continue
            bench(f"pallas bn={bn} bk={bk}",
                  functools.partial(qm.quant_matmul, fast=True, bn=bn, bk=bk),
                  x, w, bytes_moved=nbytes)
        bench("pallas default picks",
              functools.partial(qm.quant_matmul, fast=True), x, w,
              bytes_moved=nbytes)
        # the decode-shaped fused dequant-GEMV candidate (one full-K pass
        # per N stripe; DLLAMA_TPU_QUANT_KERNEL=fused) — fast (serving) and
        # exact (parity) numerics
        if qm.supports_decode((1, K), w, True):
            bench("pallas fused (fast)",
                  functools.partial(qm.quant_matmul, fast=True, fused=True),
                  x, w, bytes_moved=nbytes)
        if qm.supports_decode((1, K), w, False):
            bench("pallas fused (exact)",
                  functools.partial(qm.quant_matmul, fused=True),
                  x, w, bytes_moved=nbytes)

        bench("xla dequant+dot (fast)",
              lambda x, w: x @ dequantize_weight(w, dtype=jnp.bfloat16),
              x, w, bytes_moved=nbytes)

        bench("xla dequant bf16-scales",
              lambda x, w: x @ (w.codes.astype(jnp.bfloat16)
                                * jnp.repeat(w.scales.astype(jnp.bfloat16),
                                             32, axis=0)),
              x, w, bytes_moved=K * N + (K // 32) * N * 2)

        c4 = w.codes.astype(jnp.int4)  # packed: 0.5 B/weight in HBM
        s16 = w.scales.astype(jnp.bfloat16)
        bench("xla dequant s4 codes",
              lambda x, c, s: x @ (c.astype(jnp.bfloat16)
                                   * jnp.repeat(s, 32, axis=0)),
              x, c4, s16, bytes_moved=K * N // 2 + (K // 32) * N * 2)

        # grouped dot: batched [G,32]x[G,32,N] dots then one scale multiply
        # per (group, col) — 32x less VPU scale work than per-element
        # dequant, exact same math (sum regrouped by quant block)
        def grouped_mv(x, w):
            G = K // 32
            xg = x.reshape(G, 32).astype(jnp.bfloat16)  # [G, 32]
            cg = w.codes.reshape(G, 32, N).astype(jnp.bfloat16)
            part = jax.lax.dot_general(  # [G, N]
                xg, cg, dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            return jnp.sum(part * w.scales.astype(jnp.float32),
                           axis=0)[None, :]

        bench("grouped-dot + scale", grouped_mv, x, w, bytes_moved=nbytes)

        wd = w.codes.astype(jnp.bfloat16)
        bench("dense bf16 (2B/weight)", lambda x, w: x @ w, x, wd,
              bytes_moved=2 * K * N)
        # s8 x s8 -> s32 directly on the MXU (no converts the compiler could
        # hoist): the per-op rate bounds a w8a8 "turbo" quant mode
        xq = jnp.clip(jnp.round(x.astype(jnp.float32) * 16.0),
                      -127, 127).astype(jnp.int8)
        bench("s8xs8 MXU dot -> s32",
              lambda xq, c: jax.lax.dot_general(
                  xq, c, dimension_numbers=(((1,), (0,)), ((), ())),
                  preferred_element_type=jnp.int32), xq, w.codes,
              bytes_moved=K * N)
        # manually packed 4-bit codes (two per byte along K), unpacked on the
        # VPU in-graph: halves code HBM at the price of shift/mask VPU work
        packed = ((w.codes[0::2] + 8).astype(jnp.uint8)
                  | ((w.codes[1::2] + 8).astype(jnp.uint8) << 4))

        def unpack_mv(x, p, s):
            lo = (p & jnp.uint8(0x0F)).astype(jnp.int8) - 8
            hi = (p >> 4).astype(jnp.int8) - 8
            c = jnp.stack([lo, hi], axis=1).reshape(K, N)
            wd = c.astype(jnp.bfloat16) * jnp.repeat(s, 32, axis=0)
            return x @ wd

        bench("packed-u4 dequant+dot", unpack_mv, x, packed,
              w.scales.astype(jnp.bfloat16),
              bytes_moved=K * N // 2 + (K // 32) * N * 2)

        # multi-row activations: the verify (M=8) and prefill-chunk (M=256)
        # shapes — how the fused dequant amortizes over rows
        for M in (8, 256):
            xm = jax.random.normal(jax.random.fold_in(key, 7 * M),
                                   (M, K), jnp.bfloat16)
            bench(f"xla dequant M={M}",
                  lambda x, w: x @ dequantize_weight(w, dtype=jnp.bfloat16),
                  xm, w, bytes_moved=K * N + (K // 32) * N * 4)
            if M <= qm.FUSED_MAX_M and qm.supports_decode((M, K), w, True):
                bench(f"pallas fused M={M}",
                      functools.partial(qm.quant_matmul, fast=True,
                                        fused=True),
                      xm, w, bytes_moved=K * N + (K // 32) * N * 4)

    if as_json:
        try:
            kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — the line must still emit
            kind = ""
        print(json.dumps({"tool": "gemv_sweep", "device_kind": kind,
                          "n_lo": n_lo, "n_hi": n_hi, "rows": rows}))


if __name__ == "__main__":
    main()
