#!/usr/bin/env python
"""Route-label lint: every handler-matched route is in serve/api.py _ROUTES; the GET /debug index is closed-world both directions.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself now
lives on the shared dlint framework as the ``route-labels`` rule —
``python -m tools.dlint --only route-labels`` is the canonical entry point;
this script exists so historical CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["route-labels"])


if __name__ == "__main__":
    sys.exit(main())
