#!/usr/bin/env python
"""Route-label lint (Makefile ``lint`` target).

``serve/api.py`` folds unknown paths into the ``other`` route label so a
scanner can't explode ``dllama_http_requests_total``'s cardinality — which
only works if every route a handler actually matches on is listed in
``_ROUTES``. A handler added for ``/debug/foo`` without the ``_ROUTES``
entry silently reports its traffic as ``other`` and per-route dashboards
go blind. This lint keeps the set closed-world:

1. parse ``serve/api.py``'s AST (no imports — runnable without jax);
2. collect ``_ROUTES`` from its assignment;
3. collect every string literal that a handler compares against the
   request path (any ``==`` / ``in`` comparison whose other side mentions
   ``path``, e.g. ``self.path``, ``self._route()``, or a local ``path``);
4. every compared literal must appear in ``_ROUTES``;
5. the ``GET /debug`` index (``_DEBUG_INDEX``) is closed-world against
   ``_ROUTES``: every ``/debug/*`` route has exactly one non-empty
   description entry and every index entry is a registered route — the
   index can never silently omit (or invent) a diagnostic surface.
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
API = REPO / "dllama_tpu" / "serve" / "api.py"


def _mentions_path(node: ast.expr) -> bool:
    """True when the expression reads the request path: a name or attribute
    called ``path``, or a call of ``_route`` (the query-stripping helper)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("path", "_route"):
            return True
        if isinstance(sub, ast.Name) and sub.id == "path":
            return True
    return False


def _route_literals(node: ast.expr) -> list[str]:
    """String constants that look like routes inside a comparator."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and sub.value.startswith("/"):
            out.append(sub.value)
    return out


def main() -> int:
    tree = ast.parse(API.read_text(encoding="utf-8"), filename=str(API))

    routes: set[str] | None = None
    debug_index: dict | None = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "_ROUTES":
                    routes = set(ast.literal_eval(node.value))
                elif isinstance(tgt, ast.Name) and tgt.id == "_DEBUG_INDEX":
                    debug_index = ast.literal_eval(node.value)
    if routes is None:
        print("❌ serve/api.py: no _ROUTES assignment found", file=sys.stderr)
        return 1
    if debug_index is None:
        print("❌ serve/api.py: no _DEBUG_INDEX assignment found "
              "(the GET /debug index)", file=sys.stderr)
        return 1

    errors: list[str] = []
    compared: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left, *node.comparators]
        if not any(_mentions_path(s) for s in sides):
            continue
        for s in sides:
            if _mentions_path(s):
                continue
            for lit in _route_literals(s):
                compared.add(lit)
                if lit not in routes:
                    errors.append(
                        f"serve/api.py:{node.lineno}: handler matches "
                        f"{lit!r} but it is not in _ROUTES — its traffic "
                        f"would be folded into the 'other' label")

    # the GET /debug index ↔ _ROUTES, both directions
    debug_routes = {r for r in routes if r.startswith("/debug/")}
    for r in sorted(debug_routes - set(debug_index)):
        errors.append(f"serve/api.py: /debug route {r!r} has no "
                      f"_DEBUG_INDEX description — the GET /debug index "
                      f"would silently omit it")
    for r in sorted(set(debug_index) - debug_routes):
        errors.append(f"serve/api.py: _DEBUG_INDEX entry {r!r} is not a "
                      f"registered /debug route in _ROUTES")
    for r, desc in sorted(debug_index.items()):
        if not isinstance(desc, str) or not desc.strip():
            errors.append(f"serve/api.py: _DEBUG_INDEX[{r!r}] has an "
                          f"empty description")
    if "/debug" not in routes:
        errors.append("serve/api.py: the '/debug' index route itself is "
                      "missing from _ROUTES")

    if errors:
        for e in errors:
            print(f"❌ {e}", file=sys.stderr)
        return 1
    print(f"✅ route labels closed-world: {len(compared)} handler-matched "
          f"routes all listed in _ROUTES ({len(routes)} registered); "
          f"GET /debug index covers all {len(debug_routes)} /debug routes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
