#!/usr/bin/env python
"""Tenant decision-reason lint: every admission decision in
runtime/serving.py and serve/router.py names a reason from
tenancy.ADMIT_REASONS, every reason has a live emit site + docs, and
the dllama_tenant_* metric family is closed-world vs telemetry.SPECS
and PERF.md.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself
lives on the shared dlint framework as the ``tenant-reasons`` rule —
``python -m tools.dlint --only tenant-reasons`` is the canonical entry
point; this script exists so direct CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["tenant-reasons"])


if __name__ == "__main__":
    sys.exit(main())
