#!/usr/bin/env python
"""Kernel-choice perf sweep: one command turns a live-chip window into a
comparison table instead of a single point.

Runs ``bench.py --stage <preset>`` once per knob combo — each in its own
subprocess (wedge-isolated, same as the bench) — and prints a JSON line
per combo plus a final summary. The knobs:

  DLLAMA_TPU_QUANT_KERNEL  pallas | xla   (ops/linear.py dispatch)
  DLLAMA_BENCH_ATTN        flash  | xla   (ModelConfig.attn_impl)
  DLLAMA_BENCH_KV          bf16 | f8 | f32  (KV cache storage dtype)
  DLLAMA_TPU_QUANT_MODE    fast | exact | turbo | turbo16  (ops/linear.py)
  DLLAMA_TPU_DENSE_LOGITS  on | off      (resident bf16 head vs Q40)
  DLLAMA_TPU_SCAN_UNROLL   N             (layer-scan unroll, models/llama.py)
  DLLAMA_BENCH_WEIGHTS     q40 | bf16    (dense planes: the no-dequant
                                          streaming ceiling; 1b-only — the
                                          8b dense stack exceeds HBM and the
                                          budget check refuses it cleanly)

Usage:
  python tools/perf_matrix.py [preset] [per-stage-budget-s]
  # defaults: preset=1b (safe shape), budget=420

The reference's analogue is its Eval-ms/Sync-ms per-token table
(/root/reference/src/dllama.cpp:59-67); this sweep answers the TPU-side
question the reference never had: which of XLA-fused dequant vs the Pallas
kernel, and XLA attention vs the flash kernel, wins at each shape.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402 — the bench parent module is deliberately jax-free

# the bench presets run bf16 compute, so un-pinned rows resolve to FAST
# numerics (auto): the production config. Each other row isolates one knob
# against it. (Round-4 finding: fast quant dispatch is always the XLA fused
# dequant — the gemv sweep measured it 3-5x over the Pallas kernel — so the
# old pallas-vs-xla fast rows collapsed into one "pallas" comparison row.)
# DECISION-VALUE order, not taxonomy order: a truncated chip window (the
# round-4/5 failure mode is a wedge or a window opening minutes before the
# round ends) banks combos front-to-back, and the round's verdict rides on
# auto-vs-turbo — so those three run FIRST.
COMBOS = [
    # (label, quant_kernel, attn_impl, kv_dtype, quant_mode, dense_logits,
    #  scan_unroll, weights)
    ("auto", None, None, None, None, None, None, None),          # production
    # integer-dot turbo modes (ops/turbo.py): per-column int8 planes,
    # scales in the epilogue; a8 = s8xs8 MXU dots, a16 = bf16 activations
    ("turbo16", None, None, None, "turbo16", None, None, None),
    ("turbo", None, None, None, "turbo", None, None, None),
    ("unroll4", None, None, None, None, None, "4", None),        # layer-scan unroll
    # dense bf16 planes: the no-dequant streaming ceiling (fits HBM on the
    # 1b preset only; the 8b row fails its budget check with a clean error)
    ("bf16-dense", None, None, None, None, None, None, "bf16"),
    ("auto+f8kv", None, None, "f8", None, None, None, None),     # fp8 KV storage
    ("q40-logits", None, None, None, None, "off", None, None),   # quantized head
    ("xla-attn", None, "xla", None, None, None, None, None),     # oracle attention
    ("exact", None, None, None, "exact", None, None, None),      # parity numerics
    ("pallas", "pallas", "flash", None, None, None, None, None), # Pallas kernel
    # decode-shaped fused dequant-GEMV (one full-K pass per N stripe;
    # also turns the ragged paged attention kernel on via the shared gate)
    ("fused", "fused", None, None, None, None, None, None),
]


def run_combo(preset: str, budget: float, quant: str | None,
              attn: str | None, kv: str | None = None,
              qmode: str | None = None,
              dense_logits: str | None = None,
              scan_unroll: str | None = None,
              weights: str | None = None) -> dict:
    """Set the combo's knobs in this process's env and delegate to
    bench.run_stage (subprocess isolation, live phase tracking, stderr tail,
    kill+reap — no second implementation to drift)."""
    for var, val in (("DLLAMA_TPU_QUANT_KERNEL", quant),
                     ("DLLAMA_BENCH_ATTN", attn),
                     ("DLLAMA_BENCH_KV", kv),
                     ("DLLAMA_TPU_QUANT_MODE", qmode),
                     ("DLLAMA_TPU_DENSE_LOGITS", dense_logits),
                     ("DLLAMA_TPU_SCAN_UNROLL", scan_unroll),
                     ("DLLAMA_BENCH_WEIGHTS", weights)):
        if val:
            os.environ[var] = val
        else:
            os.environ.pop(var, None)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/dllama-xla-cache-bench")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    return bench.run_stage(preset, budget)


def main() -> None:
    preset = sys.argv[1] if len(sys.argv) > 1 else "1b"
    budget = float(sys.argv[2]) if len(sys.argv) > 2 else 420.0
    rows: dict = {}
    for label, quant, attn, kv, qmode, dense, unroll, weights in COMBOS:
        t0 = time.monotonic()
        res = run_combo(preset, budget, quant, attn, kv, qmode, dense,
                        unroll, weights)
        res["combo_s"] = round(time.monotonic() - t0, 1)
        rows[label] = res
        print(json.dumps({label: res}), flush=True)
    print(json.dumps({"preset": preset, "matrix": rows}))
    keys = ("decode_tok_per_s", "prefill_tok_per_s", "sampled_decode_tok_per_s",
            "chunked_decode_tok_per_s")
    print(f"\n{'combo':14s}" + "".join(f"{k.split('_tok')[0]:>18s}" for k in keys))
    for label, res in rows.items():
        cells = "".join(f"{res.get(k, '-'):>18}" for k in keys)
        err = f"   ({res['error'][:40]})" if res.get("error") else ""
        print(f"{label:14s}{cells}{err}")


if __name__ == "__main__":
    main()
