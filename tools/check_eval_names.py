#!/usr/bin/env python
"""Eval config-name lint: every config in telemetry.EVAL_CONFIGS is
grammar-clean, derived (not hand-copied) by its consumers (the eval
CLI's --compare grammar, the harness, bench, the quality ledger),
documented in README.md, and closed-world vs the committed
QUALITY_BASELINE.json parity keys — in both directions.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself
lives on the shared dlint framework as the ``eval-names`` rule —
``python -m tools.dlint --only eval-names`` is the canonical entry
point; this script exists so direct CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["eval-names"])


if __name__ == "__main__":
    sys.exit(main())
