#!/usr/bin/env python
"""Exception-hygiene lint (Makefile ``lint`` target).

The serving stack's fault-tolerance contract (ISSUE 2) is that no failure
is silently swallowed: a request either completes, or its waiter gets an
explicit error — never a hung ``done.wait()``. Broad exception handlers
are where that contract quietly erodes, so this lint enforces:

1. **no bare ``except:``** anywhere in ``dllama_tpu/`` — a bare clause
   catches ``KeyboardInterrupt``/``SystemExit`` and masks shutdown;
2. every ``except Exception`` / ``except BaseException`` handler in
   ``dllama_tpu/runtime/`` and ``dllama_tpu/serve/`` (the layers that own
   request lifecycles) must do at least one of:

   * **re-raise** (a ``raise`` statement anywhere in the handler body),
   * **surface the failure to a waiter** — assign to an ``.error``
     attribute or call a failure-plumbing method (``done.set``,
     ``_fail_all``, ``_fail_request``, ``_on_crash``, ``os._exit``),
   * **justify itself** with ``# noqa: BLE001`` plus a reason on the
     ``except`` line (the flake8-blind-except code, kept grep-compatible).

Pure AST + source text — no imports of the package, runnable without jax.
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "dllama_tpu"
# layers that own request lifecycles: broad handlers here must plumb the
# failure somewhere a waiter can see it
STRICT_DIRS = (PKG / "runtime", PKG / "serve")

# calls that count as "the failure reached a waiter / supervisor".
# Bare `set` is NOT enough (a telemetry gauge .set(0) or _wake.set()
# would trivially pass) — only the `done.set` chain counts.
_SURFACING_CALLS = {"_fail_all", "_fail_request", "_on_crash", "_exit"}


def _is_broad(node: ast.ExceptHandler) -> bool:
    """except Exception / except BaseException (bare handled separately)."""

    def broad_name(t: ast.expr) -> bool:
        return isinstance(t, ast.Name) and t.id in ("Exception",
                                                    "BaseException")

    t = node.type
    if t is None:
        return False
    if broad_name(t):
        return True
    return isinstance(t, ast.Tuple) and any(broad_name(e) for e in t.elts)


def _walk_same_scope(stmts):
    """Walk statements without descending into nested function/class
    definitions — a `raise` inside a callback defined in the handler
    does not surface THIS handler's failure."""
    todo = list(stmts)
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            todo.append(child)


def _handler_ok(node: ast.ExceptHandler, src_lines: list[str]) -> bool:
    line = src_lines[node.lineno - 1]
    if "noqa: BLE001" in line:
        return True
    for sub in _walk_same_scope(node.body):
        if isinstance(sub, ast.Raise):
            return True
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Attribute) and tgt.attr == "error":
                    return True
        if isinstance(sub, ast.Call):
            f = sub.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name in _SURFACING_CALLS:
                return True
            # `<...>.done.set()` — the one .set() chain that counts
            if (name == "set" and isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Attribute)
                    and f.value.attr == "done"):
                return True
    return False


def main() -> int:
    errors: list[str] = []
    n_handlers = 0
    for py in sorted(PKG.rglob("*.py")):
        src = py.read_text(encoding="utf-8")
        try:
            tree = ast.parse(src, filename=str(py))
        except SyntaxError as e:
            errors.append(f"{py.relative_to(REPO)}: unparseable: {e}")
            continue
        src_lines = src.splitlines()
        strict = any(d in py.parents for d in STRICT_DIRS)
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            rel = py.relative_to(REPO)
            if node.type is None:
                errors.append(
                    f"{rel}:{node.lineno}: bare `except:` (catches "
                    f"KeyboardInterrupt/SystemExit; name the exception)")
                continue
            if strict and _is_broad(node):
                n_handlers += 1
                if not _handler_ok(node, src_lines):
                    errors.append(
                        f"{rel}:{node.lineno}: `except Exception` must "
                        f"set a request .error, re-raise, surface via "
                        f"done.set/_fail_*, or carry `# noqa: BLE001 — "
                        f"<reason>` on the except line")
    if errors:
        for e in errors:
            print(f"❌ {e}", file=sys.stderr)
        return 1
    print(f"✅ exception hygiene: no bare excepts; {n_handlers} broad "
          f"handlers in runtime/+serve/ all surface their failures")
    return 0


if __name__ == "__main__":
    sys.exit(main())
