#!/usr/bin/env python
"""Exception-hygiene lint: no bare excepts; broad handlers in runtime//serve/ must surface their failures to a waiter.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself now
lives on the shared dlint framework as the ``exception-hygiene`` rule —
``python -m tools.dlint --only exception-hygiene`` is the canonical entry point;
this script exists so historical CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["exception-hygiene"])


if __name__ == "__main__":
    sys.exit(main())
