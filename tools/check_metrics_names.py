#!/usr/bin/env python
"""Metric-name lint: telemetry.SPECS naming convention + PERF.md docs + source literals, closed-world in both directions.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself now
lives on the shared dlint framework as the ``metrics-names`` rule —
``python -m tools.dlint --only metrics-names`` is the canonical entry point;
this script exists so historical CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["metrics-names"])


if __name__ == "__main__":
    sys.exit(main())
