#!/usr/bin/env python
"""Metric-name lint (Makefile ``lint`` target).

Closed-world in BOTH directions, all against the single declaration point
(``dllama_tpu.runtime.telemetry.SPECS``):

1. every registered metric name matches ``dllama_[a-z0-9_]+`` (the wire
   convention Prometheus relabeling and the dashboards assume; digits
   admitted for format names like ``q80``);
2. every registered name is documented in PERF.md (the telemetry section
   is the operator contract — an undocumented metric is a doc bug);
3. every quoted ``dllama_*`` metric-shaped literal in the package source
   is registered (catches typo'd or orphaned instrumentation that would
   KeyError at runtime or silently never render);
4. every ``dllama_*`` metric-shaped token in PERF.md is a registered
   family (catches stale docs that keep promising a metric the code no
   longer emits — the reverse of check 2). Prometheus-derived suffixes
   (``_bucket``/``_sum``/``_count`` of a registered histogram) are
   allowed.

Importing only the telemetry module keeps this runnable without jax.
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dllama_tpu.runtime.telemetry import SPECS  # noqa: E402

NAME_RE = re.compile(r"^dllama_[a-z0-9_]+$")
# quoted dllama_* literals in source; names continuing with '.' or '-' are
# module paths / model ids, not metrics
LITERAL_RE = re.compile(r"""["'](dllama_[a-z0-9_]+)["']""")
# package-name strings that legitimately appear quoted in source
NOT_METRICS = {"dllama_tpu"}
# non-metric literal families: model-zoo ids (zoo.py) share the prefix
NOT_METRIC_PREFIXES = ("dllama_model_",)


def _not_a_metric(lit: str) -> bool:
    return lit in NOT_METRICS or lit.startswith(NOT_METRIC_PREFIXES)


def main() -> int:
    errors: list[str] = []

    for name, spec in SPECS.items():
        if not NAME_RE.match(name):
            errors.append(f"registered metric {name!r} violates "
                          f"dllama_[a-z0-9_]+ naming")
        if spec.kind not in ("counter", "gauge", "histogram"):
            errors.append(f"{name}: unknown kind {spec.kind!r}")
        if spec.kind == "counter" and not name.endswith("_total"):
            errors.append(f"counter {name} must end in _total "
                          f"(Prometheus convention)")
        if not spec.help:
            errors.append(f"{name}: empty help text")

    perf = (REPO / "PERF.md").read_text(encoding="utf-8")
    for name in SPECS:
        if name not in perf:
            errors.append(f"metric {name} is not documented in PERF.md")

    # reverse direction: every dllama_* token PERF.md mentions must be a
    # registered family (or a histogram's derived _bucket/_sum/_count)
    derived = {base + suffix for base, spec in SPECS.items()
               if spec.kind == "histogram"
               for suffix in ("_bucket", "_sum", "_count")}
    for name in sorted(set(LITERAL_RE.findall(perf))
                       | set(re.findall(r"\b(dllama_[a-z0-9_]+)", perf))):
        if _not_a_metric(name) or name in SPECS or name in derived:
            continue
        errors.append(f"PERF.md mentions {name!r} but no such metric "
                      f"family is registered in telemetry.SPECS "
                      f"(stale doc or typo)")

    for py in sorted((REPO / "dllama_tpu").rglob("*.py")):
        for lit in LITERAL_RE.findall(py.read_text(encoding="utf-8")):
            if _not_a_metric(lit) or lit in SPECS:
                continue
            errors.append(f"{py.relative_to(REPO)}: literal {lit!r} looks "
                          f"like a metric name but is not registered in "
                          f"telemetry.SPECS")

    if errors:
        for e in errors:
            print(f"❌ {e}", file=sys.stderr)
        return 1
    print(f"✅ {len(SPECS)} metric names: convention + PERF.md docs + "
          f"source literals all consistent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
