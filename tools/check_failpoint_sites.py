#!/usr/bin/env python
"""Failpoint-site lint (Makefile ``lint`` target).

The chaos suite (tests/test_chaos.py) can only drive failure paths whose
injection sites exist and are named what the docs say they are named. The
contract is closed-world, both directions:

1. every ``failpoints.fire("<name>")`` call site in ``dllama_tpu/`` uses a
   name documented in the Site registry of ``runtime/failpoints.py``'s
   module docstring (an undocumented site is chaos coverage nobody knows
   to arm);
2. every documented site name has at least one call site (a documented
   site with no ``fire`` is a failure path the chaos tests BELIEVE they
   can drive but can't — the worst kind of rot).

Pure AST + docstring parsing — no imports of the package, runnable
without jax.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "dllama_tpu"
FAILPOINTS = PKG / "runtime" / "failpoints.py"

# docstring registry entries: "* ``name`` — description"
_REGISTRY_RE = re.compile(r"^\* ``([a-z_]+)``", re.MULTILINE)


def documented_sites() -> set[str]:
    tree = ast.parse(FAILPOINTS.read_text(encoding="utf-8"),
                     filename=str(FAILPOINTS))
    doc = ast.get_docstring(tree) or ""
    return set(_REGISTRY_RE.findall(doc))


def fired_sites() -> dict[str, list[str]]:
    """name -> ["path:lineno", ...] over every ``failpoints.fire(<const>)``
    call in the package (tests arm ad-hoc names like ``chaos.x`` through
    the registry object directly; production sites all go through the
    module-level ``failpoints.fire``)."""
    out: dict[str, list[str]] = {}
    for py in sorted(PKG.rglob("*.py")):
        if py == FAILPOINTS:
            continue  # the registry's own generic fire(name) plumbing
        tree = ast.parse(py.read_text(encoding="utf-8"), filename=str(py))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "fire"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "failpoints"):
                continue
            where = f"{py.relative_to(REPO)}:{node.lineno}"
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                print(f"❌ {where}: failpoints.fire() with a non-literal "
                      f"site name — the closed world can't see it",
                      file=sys.stderr)
                sys.exit(1)
            out.setdefault(node.args[0].value, []).append(where)
    return out


def main() -> int:
    documented = documented_sites()
    fired = fired_sites()
    errors: list[str] = []
    if not documented:
        errors.append("no Site registry entries found in "
                      "runtime/failpoints.py's module docstring "
                      "(expected '* ``name`` — ...' lines)")
    for name, sites in sorted(fired.items()):
        if name not in documented:
            errors.append(f"site {name!r} is fired at {sites[0]} but not "
                          f"documented in the failpoints.py Site registry")
    for name in sorted(documented - set(fired)):
        errors.append(f"site {name!r} is documented in the failpoints.py "
                      f"Site registry but never fired anywhere in "
                      f"dllama_tpu/ — dead chaos surface")
    if errors:
        for e in errors:
            print(f"❌ {e}", file=sys.stderr)
        return 1
    n_sites = sum(len(v) for v in fired.values())
    print(f"✅ failpoint sites closed-world: {len(fired)} names over "
          f"{n_sites} call sites, all documented (and vice versa)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
