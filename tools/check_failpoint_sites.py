#!/usr/bin/env python
"""Failpoint-site lint: every failpoints.fire() site is documented in the Site registry and every documented site fires.

Thin wrapper (Makefile ``lint`` compatibility): the scanner itself now
lives on the shared dlint framework as the ``failpoint-sites`` rule —
``python -m tools.dlint --only failpoint-sites`` is the canonical entry point;
this script exists so historical CLI invocations keep working.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.dlint import Project, run_rules  # noqa: E402


def main() -> int:
    return run_rules(Project(), only=["failpoint-sites"])


if __name__ == "__main__":
    sys.exit(main())
