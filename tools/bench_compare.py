"""Diff two bench JSON results (or two captures) stage by stage.

Round-5 helper: quantify what a change bought —

    python tools/bench_compare.py BENCH_r04_manual.json \\
        capture_artifacts/<ts>/BENCH_live.json

Accepts bench JSON files (the one-line emit), capture directories
(reads BENCH_live.json inside), or a ``PERF_BASELINE.json`` artifact
from the perf-regression sentinel (``bench.py --baseline update`` /
``tools/perf_baseline.py record``) — a baseline side is expanded back
into per-stage fields so "current run vs enforced baseline" diffs the
same way as "capture vs capture". Prints per-stage deltas for every
rate field present in both, most-improved first; the roofline fraction
ranks higher-is-better and the exposed-comm wall lower-is-better.
"""

from __future__ import annotations

import json
import os
import sys

_RATES = ("decode_tok_per_s", "prefill_tok_per_s", "sampled_decode_tok_per_s",
          "chunked_decode_tok_per_s", "paged_decode_tok_per_s",
          "agg_tok_per_s", "accepted_tok_per_s", "decode_tok_per_s_q80",
          "sessions_per_chip", "slo_compliance_min", "eval_tok_per_s",
          "jain_index")
# lower-is-better latencies (--scenario continuous/fleet TTFT + the
# tiered wave's resume TTFT; --scenario multichip exposed collective
# wall; the fleet scenario's worst SLO error-budget burn; --scenario
# eval teacher-forced perplexity): the printed pct is still
# "improvement-positive", so the sign is flipped before ranking
_LATENCIES = ("ttft_ms_p50", "ttft_ms_p95", "resume_ttft_p95_ms",
              "comm_exposed_ms", "comm_exposed_ms_off", "slo_worst_burn",
              "perplexity")
# context-only scenario fields: printed for both sides, never ranked (a
# higher occupancy or sharing count is workload-dependent, not a win/loss
# — and the fleet scenario's churn counters describe the kill/restart
# schedule, not a performance delta)
_GAUGES = ("block_occupancy_peak", "block_occupancy_mean",
           "kv_blocks_shared_peak", "prefix_reuse_tokens",
           "spec_accept_rate", "itl_p50_ms_delta",
           "wire_q80_shrink", "exposed_overlap_lower",
           "f32_tokens_identical",
           "router_retries", "router_ejects", "router_shed",
           "n_midstream_error", "readmitted",
           "total_nll_hex", "parity_drift")


def _from_baseline(doc: dict) -> dict:
    """Expand a PERF_BASELINE.json artifact (the sentinel's recorded
    side: flat ``{"<stage>.<field>": {value, ...}}`` metrics) into the
    bench-result shape this tool diffs."""
    stages: dict = {}
    out: dict = {"metric": f"baseline:{doc.get('name')}",
                 "git": doc.get("git"),
                 "device_kind": doc.get("device_kind"),
                 "stages": stages}
    for key, rec in (doc.get("metrics") or {}).items():
        scope, _, field = key.partition(".")
        if scope == "headline" and field == "roofline_fraction":
            out.setdefault("roofline", {})["roofline_fraction"] = rec["value"]
        elif scope == "family":
            fam, _, ffield = field.partition(".")
            if ffield == "roofline_fraction":
                out.setdefault("roofline", {}).setdefault(
                    "families", {})[fam] = {"roofline_fraction": rec["value"]}
        else:
            stages.setdefault(scope, {})[field] = rec["value"]
    return out


def _from_gemv_sweep(doc: dict) -> dict:
    """Expand a ``tools/gemv_sweep.py --json`` line into the bench-result
    shape: one stage per GEMV shape, one ``gbps:<variant>`` rate per swept
    kernel variant — so two sweeps diff (and rank by effective GB/s) the
    same way two bench captures do."""
    stages: dict = {}
    for row in doc.get("rows") or ():
        if row.get("gbps") is None:
            continue
        stages.setdefault(row["shape"], {})[f"gbps:{row['label']}"] = \
            row["gbps"]
    return {"metric": "gemv_sweep", "git": doc.get("git"),
            "device_kind": doc.get("device_kind"), "stages": stages}


def _load(path: str) -> dict:
    if os.path.isdir(path):
        path = os.path.join(path, "BENCH_live.json")
    with open(path) as f:
        text = f.read()
    try:
        whole = json.loads(text)
        if isinstance(whole, dict) and whole.get("tool") == "gemv_sweep":
            return _from_gemv_sweep(whole)
        if "metrics" in whole and "stages" not in whole \
                and "value" not in whole:
            return _from_baseline(whole)
        if "stages" in whole or "value" in whole:
            return whole
        # the driver's BENCH_rN.json wrapper: {n, cmd, rc, tail, parsed}
        if isinstance(whole.get("parsed"), dict):
            return whole["parsed"]
        if "tail" in whole:  # tail holds the emitted line (may be truncated)
            for line in str(whole["tail"]).splitlines()[::-1]:
                if line.startswith("{"):
                    try:
                        return json.loads(line)
                    except json.JSONDecodeError:
                        continue
    except json.JSONDecodeError:
        pass
    for line in text.splitlines()[::-1]:
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    raise SystemExit(f"no bench JSON in {path}")


def main() -> None:
    if len(sys.argv) != 3:
        raise SystemExit(__doc__)
    a, b = _load(sys.argv[1]), _load(sys.argv[2])
    print(f"A: {sys.argv[1]}  (git {a.get('git')}, {a.get('device_kind')})")
    print(f"B: {sys.argv[2]}  (git {b.get('git')}, {b.get('device_kind')})")
    # a skipped run never measured live hardware (bench.py emits
    # `skipped: true` + the reason when the backend was down, possibly
    # re-emitting an older banked capture): say so loudly — its deltas
    # are "no hardware", not a regression signal
    for tag, d in (("A", a), ("B", b)):
        if d.get("skipped"):
            print(f"⚠️ {tag} SKIPPED (no live measurement): "
                  f"{d.get('skip_reason') or d.get('error') or 'backend unavailable'}")
    if a.get("skipped") or b.get("skipped"):
        print("⚠️ deltas below compare non-live data — not a regression "
              "signal\n")
    # the eval scenario's bit-parity verdict: a side whose exact-parity
    # configs (telemetry.EVAL_PARITY — paged vs dense vs the single-seq
    # oracle, spec-on vs spec-off) disagree on total NLL is numerically
    # broken; its perplexity/eval_tok_per_s deltas describe a bug, not a
    # quality tradeoff
    for tag, d in (("A", a), ("B", b)):
        for stage, rec in sorted((d.get("stages") or {}).items()):
            if isinstance(rec, dict) and rec.get("parity_drift"):
                print(f"❌ {tag} stage '{stage}': PARITY DRIFT — "
                      f"exact-parity eval configs disagree bit-for-bit "
                      f"on total NLL; treat this side's quality numbers "
                      f"as a numerics bug, not a quality tradeoff")
    hv_a, hv_b = a.get("value") or 0, b.get("value") or 0
    if hv_a and hv_b:
        print(f"headline {a.get('metric')}: {hv_a} -> {hv_b} "
              f"({100 * (hv_b - hv_a) / hv_a:+.1f}%)\n")

    rows = []
    sa, sb = a.get("stages") or {}, b.get("stages") or {}
    for stage in sorted(set(sa) & set(sb)):
        # gbps:<variant> fields come from gemv-sweep expansion (effective
        # GB/s per kernel variant — higher is better, ranked like rates)
        sweep = sorted(k for k in set(sa[stage]) & set(sb[stage])
                       if k.startswith("gbps:"))
        for k in _RATES + tuple(sweep):
            va, vb = sa[stage].get(k), sb[stage].get(k)
            if va and vb:
                rows.append((100 * (vb - va) / va, stage, k, va, vb))
        for k in _LATENCIES:  # lower is better: +% means B got FASTER
            va, vb = sa[stage].get(k), sb[stage].get(k)
            if va and vb:
                rows.append((100 * (va - vb) / va, stage, k, va, vb))
    # roofline observatory section (higher fraction = closer to the chip
    # ceiling = better); ceiling source printed as context below when the
    # two sides measured against different ceilings
    ra, rb = a.get("roofline") or {}, b.get("roofline") or {}
    va, vb = ra.get("roofline_fraction"), rb.get("roofline_fraction")
    if va and vb:
        rows.append((100 * (vb - va) / va, "headline",
                     "roofline_fraction", va, vb))
    # per-family fractions (decode vs prefill vs paged — the paged family
    # is where the PR6 gather cost shows up; a no_evidence family has no
    # fraction and drops out of the ranking by construction)
    fa, fb = ra.get("families") or {}, rb.get("families") or {}
    for fam in sorted(set(fa) & set(fb)):
        va = (fa[fam] or {}).get("roofline_fraction")
        vb = (fb[fam] or {}).get("roofline_fraction")
        if va and vb:
            rows.append((100 * (vb - va) / va, f"family:{fam}",
                         "roofline_fraction", va, vb))
    if not rows:
        print("no overlapping measured rates")
        return
    for pct, stage, k, va, vb in sorted(rows, reverse=True):
        print(f"  {stage:10s} {k:28s} {va:>10} -> {vb:>10}  ({pct:+.1f}%)")
    gauges = []
    for stage in sorted(set(sa) & set(sb)):
        for k in _GAUGES:
            va, vb = sa[stage].get(k), sb[stage].get(k)
            if va is not None and vb is not None:
                gauges.append((stage, k, va, vb))
    if (ra.get("ceiling_source") or rb.get("ceiling_source")) \
            and ra.get("ceiling_source") != rb.get("ceiling_source"):
        gauges.append(("headline", "roofline_ceiling_source",
                       ra.get("ceiling_source"), rb.get("ceiling_source")))
    if gauges:
        print("  -- context (not ranked) --")
        for stage, k, va, vb in gauges:
            print(f"  {stage:10s} {k:28s} {va!s:>10} -> {vb!s:>10}")


if __name__ == "__main__":
    main()
