#!/usr/bin/env python
"""Generate the committed deterministic eval fixture.

``tests/goldens/eval_tiny.jsonl`` is the quality observatory's pinned
dataset: a handful of token-id sequences (no tokenizer needed — the
``tokens`` entry form of runtime/evalharness.load_dataset) sized for the
tests' tiny toy models. Token ids stay below 128 so the fixture works
against every tiny_header_params() vocab in tests/helpers.py, and the
generator is a seeded LCG — rerunning this script reproduces the file
byte for byte, so the golden NLL asserted in tests/test_evalharness.py
stays pinned to committed bytes, not to a random stream.

Rerun ``python tools/make_eval_fixture.py [--seed N]`` to regenerate
(the default seed is the committed fixture's).
"""

from __future__ import annotations

import argparse
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_SEED = 0xE7A1
VOCAB_CAP = 128  # ids < min tiny-model vocab (tests/helpers.py default)
# lengths chosen to cross prefill-chunk boundaries in the tiny configs:
# shorter than one chunk, exactly around bucket edges, and multi-chunk
SEQ_LENS = (12, 17, 24, 31, 40, 13)


def lcg(seed: int):
    """Tiny deterministic generator (numerical-recipes constants) — no
    dependence on random-module versioning for a committed fixture."""
    state = seed & 0xFFFFFFFF
    while True:
        state = (1664525 * state + 1013904223) & 0xFFFFFFFF
        yield state >> 16


def make_seqs(seed: int) -> list[dict]:
    g = lcg(seed)
    seqs = []
    for i, n in enumerate(SEQ_LENS):
        toks = [next(g) % VOCAB_CAP for _ in range(n)]
        seqs.append({"id": f"seq{i}", "tokens": toks})
    return seqs


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=lambda s: int(s, 0), default=DEFAULT_SEED,
                    help="LCG seed (default: the committed fixture's)")
    ap.add_argument("--out", default=os.path.join(
        REPO, "tests", "goldens", "eval_tiny.jsonl"))
    args = ap.parse_args()
    seqs = make_seqs(args.seed)
    with open(args.out, "w", encoding="utf-8") as f:
        for s in seqs:
            f.write(json.dumps(s) + "\n")
    n_tok = sum(len(s["tokens"]) for s in seqs)
    print(f"wrote {args.out}: {len(seqs)} seqs, {n_tok} tokens "
          f"(seed {args.seed:#x})")


if __name__ == "__main__":
    main()
