#!/usr/bin/env python
"""Regenerate tests/goldens/synthetic.xplane.pb — the known-answer trace for
tests/test_profiling.py's xplane-parsing tier.

The fixture encodes two device lanes with hand-computable eval/sync content
(all numbers in picoseconds; 1 ms = 1e9 ps):

* ``/device:TPU:0`` / "XLA Ops":
    - ``fusion.1``        [0, 4e9]        → 4 ms eval
    - ``all-reduce.1``    [4e9, 6e9]      → 2 ms sync
    - ``wait:rendezvous`` [4.5e9, 5.5e9]  → nested inside the all-reduce:
      must NOT double-count (union_span)
    - ``fusion.2``        [5e9, 7e9]      → overlaps the sync span; the
      overlapped 1 ms counts once, as sync → eval contributes 1 ms
    - ``ExecuteHelper``   [0, 10e9]       → runtime noise, excluded
* ``/device:TPU:1`` / "XLA Ops":
    - ``fusion.3``        [0, 3e9]        → 3 ms eval
    - ``psum.3``          [3e9, 4e9]      → 1 ms sync (CPU-backend thunk name)
* ``/host:CPU`` plane: one event on a non-device lane — must be ignored.

With ``n_steps=2``: sync = (2+1)/2 lanes/2 steps = 0.75 ms,
eval = ((4+1)+3)/2/2 = 2.0 ms (test_profiling asserts these exactly).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dllama_tpu.runtime.profiling import _load_xplane  # noqa: E402


def build() -> bytes:
    # reuse the lazy proto loader so the generator and the parser can never
    # disagree about which xplane_pb2 they use
    import importlib

    _load_xplane.__globals__["_xplane_pb2"] = None
    try:
        _load_xplane(os.devnull)
    except Exception:
        pass  # devnull parses as an empty XSpace or raises; either is fine
    pb = _load_xplane.__globals__["_xplane_pb2"]
    assert pb is not None, "xplane proto unavailable"

    xs = pb.XSpace()

    def add_plane(name: str, line_name: str, events: list[tuple[str, int, int]]):
        plane = xs.planes.add()
        plane.name = name
        line = plane.lines.add()
        line.name = line_name
        for mid, (ev_name, start, dur) in enumerate(events, start=1):
            plane.event_metadata[mid].id = mid
            plane.event_metadata[mid].name = ev_name
            ev = line.events.add()
            ev.metadata_id = mid
            ev.offset_ps = start
            ev.duration_ps = dur

    ms = 10 ** 9  # ps per ms
    add_plane("/device:TPU:0", "XLA Ops", [
        ("fusion.1", 0, 4 * ms),
        ("all-reduce.1", 4 * ms, 2 * ms),
        ("wait:rendezvous", 4 * ms + ms // 2, ms),
        ("fusion.2", 5 * ms, 2 * ms),
        ("ExecuteHelper", 0, 10 * ms),
    ])
    add_plane("/device:TPU:1", "XLA Ops", [
        ("fusion.3", 0, 3 * ms),
        ("psum.3", 3 * ms, ms),
    ])
    add_plane("/host:CPU", "python threads", [
        ("fusion.9", 0, 50 * ms),
    ])
    return xs.SerializeToString()


def main() -> int:
    out = os.path.join(os.path.dirname(__file__), "..", "tests", "goldens",
                       "synthetic.xplane.pb")
    data = build()
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {os.path.normpath(out)} ({len(data)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
