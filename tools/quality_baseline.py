#!/usr/bin/env python
"""Quality-regression sentinel: record a perplexity baseline from the
eval harness's JSON output and gate later runs against it.

``tools/perf_baseline.py`` guards speed; nothing guarded whether a
promoted config still *predicts well* — a quant or kernel change could
trade perplexity for throughput and stay green. This tool is the
quality half of the promotion ledger:

    python -m dllama_tpu eval --model m.m --data d.jsonl --json > R.json
    python tools/quality_baseline.py record R.json --name r01
    python tools/quality_baseline.py check  R.json

``record`` writes ``QUALITY_BASELINE.json`` (repo root;
``--baseline-file`` overrides): per-dataset perplexity + the documented
tolerance, plus the recorded per-config total-NLL hexes for reference.
``check`` exits 1 naming every metric whose perplexity regressed beyond
the tolerance — and, independently, whenever two exact-parity configs
in the CURRENT run (telemetry.EVAL_PARITY: paged vs dense-vs-single,
spec-on vs spec-off) disagree bit-for-bit on total NLL. Parity is
gated within one run, never across runs: a kernel change may move NLL
bits while staying inside the perplexity tolerance, but two configs of
the SAME build must agree exactly or something is numerically wrong.

With no result file, ``check``/``record`` run the built-in fixture
eval: a deterministically-seeded tiny model scored on
``tests/goldens/eval_tiny.jsonl`` under every config in
telemetry.EVAL_CONFIGS — the hermetic CI gate behind ``make
quality-check`` (no model download, no hardware assumption).

Same verdict grammar as the perf sentinel: ``regressions`` /
``improvements`` / ``within_noise`` / ``no_evidence``. A skipped or
absent measurement is **no evidence** — never a pass, never a fail —
and a corrupt baseline or result file is rc 2, never a quality verdict.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # the parity check reads telemetry.EVAL_PARITY
DEFAULT_BASELINE = os.path.join(REPO, "QUALITY_BASELINE.json")
FIXTURE = os.path.join(REPO, "tests", "goldens", "eval_tiny.jsonl")

# the documented tolerance: per-dataset perplexity may move this much
# (relative) before the gate goes red. Teacher-forced NLL on a fixed
# dataset is far less noisy than a wall-clock benchmark — float-math
# reassociation across jax/XLA versions and backends is the only
# legitimate wiggle, and it is well under 2%.
QUALITY_TOL = 0.02

BUILTIN_SEED = 0x5EED  # the built-in fixture eval's tiny-model RNG seed


def last_json_line(text: str) -> dict | None:
    """The last parseable JSON-object line in ``text`` (the eval CLI
    emits exactly one with ``--json``; logs may surround it), or None."""
    for line in str(text).splitlines()[::-1]:
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                return obj
    return None


def load_eval_json(path: str) -> dict:
    """An eval result from disk: the ``--json`` one-line emit (a single
    run summary, optionally carrying a ``compare`` sub-run) or this
    tool's own multi-run shape (``{"runs": [...]}``)."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        whole = json.loads(text)
        if isinstance(whole, dict) and ("runs" in whole
                                        or "dataset" in whole):
            return whole
    except json.JSONDecodeError:
        pass
    found = last_json_line(text)
    if found is not None:
        return found
    raise ValueError(f"no eval JSON found in {path}")


def iter_runs(result: dict):
    """Every complete run summary in a result doc, compare sub-runs
    included. Partial (aborted) runs contribute NOTHING — a truncated
    perplexity is no evidence, not a number."""
    runs = result.get("runs") if isinstance(result.get("runs"), list) \
        else [result]
    for run in runs:
        if not isinstance(run, dict) or run.get("partial"):
            continue
        if "dataset" in run and "config" in run:
            yield run
        sub = run.get("compare")
        if isinstance(sub, dict) and not sub.get("partial"):
            yield sub


def extract_metrics(result: dict) -> dict:
    """Flatten a result into the sentinel's comparable metrics:
    ``{"<dataset>.perplexity": {value, higher_better, noise_frac}}``.
    One perplexity per dataset — configs are exact-parity by contract,
    so any complete run's number stands for all of them (the parity
    gate, not this one, catches disagreement)."""
    out: dict = {}
    for run in iter_runs(result):
        key = f"{run['dataset']}.perplexity"
        v = run.get("perplexity")
        if v is None or key in out:
            continue
        v = float(v)
        if math.isfinite(v):
            out[key] = {"value": v, "higher_better": False,
                        "noise_frac": QUALITY_TOL}
    return out


def extract_parity(result: dict) -> dict:
    """Per-dataset map of config → total-NLL hex from every complete
    run in the result: ``{"eval_tiny": {"single": "0x1...", ...}}``."""
    out: dict = {}
    for run in iter_runs(result):
        hexes = out.setdefault(run["dataset"], {})
        if run.get("total_nll_hex"):
            hexes[run["config"]] = run["total_nll_hex"]
    return out


def check_parity(result: dict) -> list[dict]:
    """Within-run bit-parity over telemetry.EVAL_PARITY: every pair of
    exact-parity configs present in the CURRENT result must agree on
    total NLL to the bit. Returns one drift record per violated pair."""
    from dllama_tpu.runtime import telemetry

    drifts = []
    for dataset, hexes in sorted(extract_parity(result).items()):
        for a, b in telemetry.EVAL_PARITY:
            ha, hb = hexes.get(a), hexes.get(b)
            if ha is not None and hb is not None and ha != hb:
                drifts.append({"dataset": dataset, "configs": (a, b),
                               "hex": (ha, hb)})
    return drifts


def write_baseline(doc: dict, path: str) -> None:
    """THE baseline writer (same byte-stable format discipline as
    tools/perf_baseline.write_baseline — committed files diff cleanly)."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"✅ baseline '{doc['name']}' → {path} "
          f"({len(doc['metrics'])} metrics)")


def make_baseline(result: dict, name: str, source: str = "") -> dict:
    metrics = extract_metrics(result)
    if not metrics:
        raise ValueError("eval result carries no complete runs to "
                         "baseline (aborted/partial runs are no "
                         "evidence)")
    return {
        "name": name,
        "recorded_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "source": source,
        "tolerance_frac": QUALITY_TOL,
        "metrics": metrics,
        # recorded per-config total-NLL hexes: documentation of the
        # bit-exact state at record time (parity is GATED within each
        # check run, not against these — a legitimate kernel change may
        # move the bits while staying inside the tolerance)
        "parity": extract_parity(result),
    }


def compare(result: dict, baseline: dict) -> dict:
    """Every baseline metric against the current result. Verdict
    grammar matches tools/perf_baseline.compare: only ``regressions``
    can fail a check; ``no_evidence`` never passes or fails one."""
    current = extract_metrics(result)
    out: dict = {"baseline_name": baseline.get("name"),
                 "regressions": [], "improvements": [],
                 "within_noise": [], "no_evidence": [],
                 "parity_drift": []}
    for key, base in sorted((baseline.get("metrics") or {}).items()):
        cur = current.get(key)
        if cur is None:
            out["no_evidence"].append({
                "metric": key, "baseline": base["value"],
                "reason": "metric not measured in this run"})
            continue
        bv, cv = base["value"], cur["value"]
        thresh = max(base.get("noise_frac", QUALITY_TOL),
                     cur.get("noise_frac", QUALITY_TOL))
        # perplexity is lower-is-better and never legitimately zero;
        # improvement-positive delta like the perf sentinel's
        delta = (bv - cv) / bv if bv else 0.0
        rec = {"metric": key, "baseline": bv, "current": cv,
               "delta_frac": round(delta, 4), "threshold_frac": thresh}
        if delta < -thresh:
            out["regressions"].append(rec)
        elif delta > thresh:
            out["improvements"].append(rec)
        else:
            out["within_noise"].append(rec)
    out["parity_drift"] = check_parity(result)
    out["verdict"] = ("parity_drift" if out["parity_drift"]
                      else "regression" if out["regressions"]
                      else "no_evidence" if not (out["within_noise"]
                                                 or out["improvements"])
                      else "ok")
    return out


def format_report(cmp: dict) -> str:
    lines = [f"quality-baseline check vs '{cmp.get('baseline_name')}': "
             f"{cmp['verdict'].upper()}"]
    for d in cmp["parity_drift"]:
        a, b = d["configs"]
        ha, hb = d["hex"]
        lines.append(f"  ❌ PARITY DRIFT {d['dataset']}: {a} ({ha}) != "
                     f"{b} ({hb}) — exact-parity configs disagree "
                     f"bit-for-bit; this is a numerics bug, not a "
                     f"quality tradeoff")
    for r in cmp["regressions"]:
        lines.append(f"  ❌ REGRESSED {r['metric']}: {r['baseline']} -> "
                     f"{r['current']} ({100 * r['delta_frac']:+.2f}%, "
                     f"threshold ±{100 * r['threshold_frac']:.0f}%)")
    for r in cmp["improvements"]:
        lines.append(f"  ✅ improved {r['metric']}: {r['baseline']} -> "
                     f"{r['current']} ({100 * r['delta_frac']:+.2f}%)")
    for r in cmp["within_noise"]:
        lines.append(f"  · within noise {r['metric']}: {r['baseline']} -> "
                     f"{r['current']} ({100 * r['delta_frac']:+.2f}% of "
                     f"±{100 * r['threshold_frac']:.0f}%)")
    for r in cmp["no_evidence"]:
        lines.append(f"  ∅ no evidence {r['metric']} "
                     f"(baseline {r['baseline']}): {r['reason']}")
    if cmp["verdict"] == "no_evidence":
        lines.append("  (nothing measured overlaps the baseline — not a "
                     "pass, not a fail)")
    return "\n".join(lines)


def run_builtin() -> dict:
    """The hermetic fixture eval behind ``make quality-check``: a
    deterministically-seeded tiny model (tests/helpers) scored on the
    committed fixture under EVERY config in telemetry.EVAL_CONFIGS, so
    one invocation produces both the perplexity evidence and all the
    parity hexes. CPU-safe and model-download-free by construction."""
    import tempfile

    import numpy as np

    sys.path.insert(0, os.path.join(REPO, "tests"))
    sys.path.insert(0, REPO)
    from helpers import (byte_vocab_tokenizer, tiny_header_params,
                         write_tiny_model)

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime import evalharness, telemetry
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.runtime.serving import BatchScheduler

    seqs = evalharness.load_dataset(FIXTURE)
    runs = []
    with tempfile.TemporaryDirectory() as d:
        mpath = os.path.join(d, "m.m")
        tpath = os.path.join(d, "t.t")
        write_tiny_model(mpath, tiny_header_params(seq_len=64),
                         np.random.RandomState(BUILTIN_SEED))
        tfile.write_tfile(tpath, byte_vocab_tokenizer())
        for config in telemetry.EVAL_CONFIGS:
            kw = {}
            if config in ("paged", "paged_spec"):
                kw["kv_block_size"] = 8
            if config == "paged_spec":
                kw["spec_lookup"] = 4
            eng = InferenceEngine(mpath, tpath, tp=1, **kw)
            sched = None
            try:
                if config == "single":
                    run = evalharness.run_eval(seqs, dataset="eval_tiny",
                                               config=config, engine=eng)
                else:
                    sched = BatchScheduler(eng, n_slots=4)
                    run = evalharness.run_eval(seqs, dataset="eval_tiny",
                                               config=config, sched=sched)
            finally:
                if sched is not None:
                    sched.close()
                eng.close()
            print(f"· builtin eval [{config}]: perplexity "
                  f"{run['perplexity']:.4f} ({run['total_nll_hex']})",
                  file=sys.stderr)
            runs.append(run)
    return {"runs": runs, "builtin_seed": BUILTIN_SEED}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", choices=("record", "check"))
    ap.add_argument("result", nargs="?", default=None,
                    help="eval --json output (default: run the built-in "
                         "fixture eval across every config)")
    ap.add_argument("--name", default=None,
                    help="baseline name (record mode; default: result "
                         "file stem, or 'builtin')")
    ap.add_argument("--baseline-file", default=DEFAULT_BASELINE)
    args = ap.parse_args()

    if args.result is None:
        result = run_builtin()
        source = "builtin fixture eval (tests/goldens/eval_tiny.jsonl)"
    else:
        try:
            result = load_eval_json(args.result)
        except (OSError, ValueError) as e:
            # missing/corrupt RESULT is a filesystem error, not a
            # quality verdict: named rc 2, never the regression exit
            print(f"❌ result file unusable: {e}", file=sys.stderr)
            return 2
        source = args.result
    if args.mode == "record":
        name = args.name or (os.path.splitext(
            os.path.basename(args.result))[0] if args.result else "builtin")
        try:
            doc = make_baseline(result, name, source=source)
        except ValueError as e:
            print(f"❌ result file unusable: {e}", file=sys.stderr)
            return 2
        write_baseline(doc, args.baseline_file)
        return 0

    try:
        with open(args.baseline_file, encoding="utf-8") as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        # unreadable OR corrupt: a named rc-2, never a traceback a CI
        # gate misreads as a quality regression
        print(f"❌ baseline file unusable: {e}", file=sys.stderr)
        return 2
    cmp = compare(result, baseline)
    print(format_report(cmp))
    return 1 if (cmp["regressions"] or cmp["parity_drift"]) else 0


if __name__ == "__main__":
    sys.exit(main())
