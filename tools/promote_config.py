#!/usr/bin/env python
"""Promote the winning kernel-choice combo from a perf_matrix sweep.

Reads one or more perf_matrix logs (the JSON line per combo that
tools/perf_matrix.py prints), picks the combo with the best
``decode_tok_per_s`` for the 8b preset (falling back to 1b when 8b never
measured), and — when the winner beats the production ``auto`` row by at
least ``MIN_GAIN`` — writes ``bench_promoted.json`` at the repo root:

    {"env": {"DLLAMA_TPU_QUANT_MODE": "turbo16", ...},
     "evidence": {...}, "combo": "turbo16", "preset": "8b"}

bench.py applies those env knobs to its measurement children (recording
the promotion in its output), so the driver's end-of-round bench measures
the promoted serving config with full provenance (VERDICT r4 next #1:
"winning config promoted to default and recorded").

Numerics guard: combos that change quant numerics (turbo/turbo16/exact)
are only eligible when their drift class is pre-validated — the round-5
CPU gate measured turbo/turbo16 perplexity drift vs the reference binary
at the same magnitude as the default fast mode's (PERF.md round-5 ledger),
so both are eligible; combos that change only kernel/layout knobs
(attn/kv/scan-unroll/logits) are always eligible.

Usage: python tools/promote_config.py matrix_8b.log [matrix_1b.log ...]
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MIN_GAIN = 1.10  # winner must beat auto by >=10% to displace the default

# combo label -> env knobs (mirrors tools/perf_matrix.py COMBOS)
COMBO_ENV = {
    "auto": {},
    "pallas": {"DLLAMA_TPU_QUANT_KERNEL": "pallas", "DLLAMA_BENCH_ATTN": "flash"},
    "xla-attn": {"DLLAMA_BENCH_ATTN": "xla"},
    "exact": {"DLLAMA_TPU_QUANT_MODE": "exact"},
    "auto+f8kv": {"DLLAMA_BENCH_KV": "f8"},
    "q40-logits": {"DLLAMA_TPU_DENSE_LOGITS": "off"},
    "unroll4": {"DLLAMA_TPU_SCAN_UNROLL": "4"},
    "turbo": {"DLLAMA_TPU_QUANT_MODE": "turbo"},
    "turbo16": {"DLLAMA_TPU_QUANT_MODE": "turbo16"},
    # decode-shaped fused dequant-GEMV (ops/quant_matmul._decode_kernel):
    # exact-mode bit-parity with the XLA fused-dequant reference, fast-mode
    # drift same class as `fast` — a kernel choice, always eligible
    "fused": {"DLLAMA_TPU_QUANT_KERNEL": "fused"},
    # dense bf16 planes: exact numerics (no quantization), 2x the HBM —
    # only ever wins the 1b preset (the 8b dense stack exceeds HBM, so the
    # 8b-first promotion logic keeps q40 for the headline shape)
    "bf16-dense": {"DLLAMA_BENCH_WEIGHTS": "bf16"},
}
# Promotion-eligible combos: kernel/layout knobs (bit-preserving or
# value-identical) plus the numerics-changing modes whose drift class the
# round-5 CPU gate validated (turbo/turbo16 ppl drift ≈ fast's, PERF.md).
# Excluded: `exact` (a parity mode, not a serving config), `auto+f8kv`
# (fp8 KV storage is a lossy numerics change with no drift gate yet), and
# `bf16-dense` (a promoted DLLAMA_BENCH_WEIGHTS would break the 8b
# headline stages — the dense 8b stack exceeds HBM; it stays a
# diagnostic row).
ELIGIBLE = set(COMBO_ENV) - {"exact", "auto+f8kv", "bf16-dense"}


def parse_matrix(path: str) -> tuple[str | None, dict]:
    """Last full-matrix line wins; fall back to accumulating combo lines."""
    preset, rows = None, {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if "matrix" in obj:
                    preset, rows = obj.get("preset"), obj["matrix"]
                elif len(obj) == 1:
                    (label, res), = obj.items()
                    if isinstance(res, dict):
                        rows[label] = res
    except OSError:
        pass
    return preset, rows


def main() -> None:
    paths = sys.argv[1:]
    if not paths:
        print(json.dumps({"promoted": False, "reason": "no matrix logs given"}))
        return
    cands = []
    for path in paths:
        preset, rows = parse_matrix(path)
        if preset is None:
            # truncated log (outer timeout killed perf_matrix before its
            # summary line): fall back to the conventional file name,
            # matrix_<preset>.log, so the 8b-first priority still holds
            base = os.path.basename(path)
            for p in ("8b", "1b", "tiny"):
                if p in base:
                    preset = p
                    break
        auto = (rows.get("auto") or {}).get("decode_tok_per_s")
        if not auto:
            continue
        for label, res in rows.items():
            v = res.get("decode_tok_per_s")
            if v and label in ELIGIBLE and label != "auto":
                cands.append({"combo": label, "preset": preset,
                              "decode_tok_per_s": v,
                              "auto_decode_tok_per_s": auto,
                              "gain": round(v / auto, 4),
                              "source": os.path.basename(path)})
    # the 8b (BASELINE-shape) verdict outranks 1b; within a preset, max gain
    pool = [c for c in cands if c["preset"] == "8b"] or cands
    best = max(pool, key=lambda c: c["gain"], default=None)
    out_path = os.path.join(REPO, "bench_promoted.json")
    if best is None or best["gain"] < MIN_GAIN:
        # no winner: remove any stale promotion so bench measures `auto`
        if os.path.exists(out_path):
            os.remove(out_path)
        print(json.dumps({"promoted": False, "best": best,
                          "min_gain": MIN_GAIN}))
        return
    record = {"env": COMBO_ENV[best["combo"]], "combo": best["combo"],
              "preset": best["preset"], "evidence": best}
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({"promoted": True, **record}))


if __name__ == "__main__":
    main()
