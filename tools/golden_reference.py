#!/usr/bin/env python
"""Generate golden transcripts by running the reference dllama C++ binary.

Usage:
    python tools/golden_reference.py [--bin /path/to/dllama] [--out tests/goldens]

Builds the synthetic .m/.t assets from tests/golden_assets.py, runs the
reference binary in ``inference`` (greedy, fixed seed) and ``perplexity``
modes, parses the per-token pieces from stdout, and writes one JSON golden per
variant. The committed goldens are then replayed by
tests/test_golden_reference.py against the TPU engine — cross-implementation
token parity (the macbeth.sh strategy, reference examples/macbeth.sh:1-60,
minus the need for a real checkpoint).

Reference quirk captured in the goldens (and reproduced by the test): the
inference driver seeds decode with ``inputTokens[pos + 1]`` after prefill
(reference src/dllama.cpp:54) — one slot past the last prompt token, which in
practice is a zero-initialized vector element. So the last prompt token is
never evaluated and the first decode input is token id 0. The golden records
``effective_seed_token`` so the test drives the engine identically.
"""

from __future__ import annotations

import argparse
import json
import re
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))

import golden_assets  # noqa: E402

PRED_RE = re.compile(r"^🔶 Pred.*")


def run_inference(bin_path: str, m: Path, t: Path, buffer_ft: str,
                  steps: int, temperature: float = 0.0,
                  topp: float = 0.9) -> list[str]:
    cmd = [
        bin_path, "inference",
        "--model", str(m), "--tokenizer", str(t),
        "--prompt", golden_assets.PROMPT,
        "--steps", str(steps),
        "--seed", str(golden_assets.SAMPLER_SEED),
        "--temperature", str(temperature),
        "--topp", str(topp),
        "--nthreads", "1",
        "--buffer-float-type", buffer_ft,
        "--max-seq-len", "0",
    ]
    out = subprocess.run(cmd, capture_output=True, timeout=300)
    if out.returncode != 0:
        raise RuntimeError(
            f"reference inference failed rc={out.returncode}\n"
            f"stdout: {out.stdout.decode(errors='replace')[-2000:]}\n"
            f"stderr: {out.stderr.decode(errors='replace')[-2000:]}")
    pieces = []
    for line in out.stdout.decode(errors="replace").split("\n"):
        if line.startswith("🔶 Pred"):
            parts = line.split(" | ")
            assert len(parts) == 3, f"unparseable pred line: {line!r}"
            pieces.append(parts[2])
    return pieces


def run_perplexity(bin_path: str, m: Path, t: Path, buffer_ft: str,
                   prompt: str | None = None) -> dict:
    prompt = prompt if prompt is not None else golden_assets.PROMPT * 4
    cmd = [
        bin_path, "perplexity",
        "--model", str(m), "--tokenizer", str(t),
        "--prompt", prompt,
        "--nthreads", "1",
        "--buffer-float-type", buffer_ft,
    ]
    out = subprocess.run(cmd, capture_output=True, timeout=300)
    if out.returncode != 0:
        raise RuntimeError(
            f"reference perplexity failed rc={out.returncode}\n"
            f"stderr: {out.stderr.decode(errors='replace')[-2000:]}")
    text = out.stdout.decode(errors="replace")
    ppl = float(re.search(r"perplexity: ([0-9.]+)", text).group(1))
    avg = float(re.search(r"avgLogProb: (-?[0-9.]+)", text).group(1))
    return {"prompt": prompt, "perplexity": ppl, "avg_log_prob": avg}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bin", default="/tmp/ref-build/dllama")
    ap.add_argument("--out", default=str(golden_assets.GOLDEN_DIR))
    ap.add_argument("--only", default=None,
                    choices=list(golden_assets.VARIANTS),
                    help="regenerate just this variant (leave others alone)")
    args = ap.parse_args()

    out_dir = Path(args.out)
    out_dir.mkdir(parents=True, exist_ok=True)

    with tempfile.TemporaryDirectory() as td:
        tmp = Path(td)
        for variant, spec in golden_assets.VARIANTS.items():
            if args.only and variant != args.only:
                continue
            m, t, m_sha, t_sha = golden_assets.build_assets(variant, tmp)
            steps = golden_assets.variant_steps(variant)
            pieces = ([] if spec.get("ppl_only")
                      else run_inference(args.bin, m, t,
                                         spec["buffer_float_type"], steps,
                                         spec.get("temperature", 0.0),
                                         spec.get("topp", 0.9)))
            ppl = run_perplexity(args.bin, m, t, spec["buffer_float_type"],
                                 prompt=spec.get("ppl_prompt"))
            golden = {
                "variant": variant,
                "prompt": golden_assets.PROMPT,
                "steps": steps,
                "sampler_seed": golden_assets.SAMPLER_SEED,
                "temperature": spec.get("temperature", 0.0),
                "topp": spec.get("topp", 0.9),
                "buffer_float_type": spec["buffer_float_type"],
                "effective_seed_token": 0,  # dllama.cpp:54 off-by-one, see module doc
                "m_sha256": m_sha,
                "t_sha256": t_sha,
                "pieces": pieces,
                "perplexity": ppl,
            }
            path = out_dir / f"{variant}.json"
            path.write_text(json.dumps(golden, indent=1, ensure_ascii=False) + "\n")
            print(f"{variant}: {len(pieces)} pieces, ppl={ppl['perplexity']:.4f}"
                  f" -> {path}")


if __name__ == "__main__":
    main()
