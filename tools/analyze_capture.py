#!/usr/bin/env python
"""Summarize a chip_watch capture (bench_results/capture_*/) into the
comparison table the round changelog needs: measured decode/prefill vs the
bench's own roofline and the BASELINE north star, per preset and per
perf-matrix combo.

Usage: python tools/analyze_capture.py [capture_dir]
       (default: newest bench_results/capture_*)
"""

from __future__ import annotations

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NORTH_STAR = 1000.0  # tok/s, 8B Q40 — BASELINE.json (v5e-8 aggregate)


def _load_bench(path: str) -> dict | None:
    try:
        with open(path) as f:
            for line in f.read().splitlines()[::-1]:
                if line.startswith("{"):
                    try:
                        return json.loads(line)
                    except json.JSONDecodeError:
                        continue  # mid-write/truncated line: keep scanning
    except OSError:
        return None
    return None


def _matrix_rows(path: str) -> dict:
    rows: dict = {}
    try:
        with open(path) as f:
            for line in f:
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "matrix" in obj:
                    return obj["matrix"]
                for k, v in obj.items():
                    if isinstance(v, dict):
                        rows[k] = v
    except OSError:
        pass
    return rows


def main() -> None:
    if len(sys.argv) > 1:
        cdir = sys.argv[1]
    else:
        caps = sorted(glob.glob(os.path.join(REPO, "bench_results",
                                             "capture_*")))
        if not caps:
            print("no capture yet (bench_results/capture_*) — chip never "
                  "answered; see bench_results/probe_log.jsonl")
            return
        cdir = caps[-1]
    print(f"capture: {cdir}\n")

    bench = _load_bench(os.path.join(cdir, "BENCH_live.json"))
    if bench:
        print(f"headline: {bench.get('metric')} = {bench.get('value')} "
              f"{bench.get('unit')}  (vs north star {NORTH_STAR:.0f}: "
              f"{100 * float(bench.get('value') or 0) / NORTH_STAR:.1f}%)")
        roof = bench.get("roofline_decode_tok_per_s")
        if roof:
            print(f"roofline (1-chip HBM): {roof} tok/s -> measured/roofline "
                  f"= {100 * float(bench.get('value') or 0) / roof:.1f}%")
        print(f"prefill MFU: {bench.get('prefill_mfu')}  "
              f"HBM util (decode): {bench.get('hbm_util_decode')}")
        for name, st in (bench.get("stages") or {}).items():
            keys = ("quant_mode", "decode_tok_per_s", "prefill_tok_per_s",
                    "sampled_decode_tok_per_s", "chunked_decode_tok_per_s",
                    "verify_k4_over_decode", "hbm_need_gb", "phase", "error")
            cells = "  ".join(f"{k}={st[k]}" for k in keys if k in st)
            print(f"  stage {name}: {cells}")
    else:
        print("no BENCH_live.json in capture")

    for preset in ("1b", "8b"):
        rows = _matrix_rows(os.path.join(cdir, f"matrix_{preset}.log"))
        if not rows:
            continue
        print(f"\nperf matrix ({preset}):")
        print(f"  {'combo':14s} {'decode':>10s} {'prefill':>10s}")
        for label, res in rows.items():
            print(f"  {label:14s} {str(res.get('decode_tok_per_s', '-')):>10s}"
                  f" {str(res.get('prefill_tok_per_s', '-')):>10s}"
                  + (f"   ({res['error'][:40]})" if res.get("error") else ""))

    tpu_log = os.path.join(cdir, "pytest_tpu.log")
    if os.path.exists(tpu_log):
        with open(tpu_log) as f:
            tail = f.read().splitlines()[-3:]
        print("\ntpu tier: " + " / ".join(tail))


if __name__ == "__main__":
    main()
