#!/usr/bin/env python
"""Summarize a chip_watch capture (bench_results/capture_*/) into the
comparison table the round changelog needs: measured decode/prefill vs the
bench's own roofline and the BASELINE north star, per preset and per
perf-matrix combo.

Usage: python tools/analyze_capture.py [capture_dir]
       (default: newest bench_results/capture_*)
"""

from __future__ import annotations

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NORTH_STAR = 1000.0  # tok/s, 8B Q40 — BASELINE.json (v5e-8 aggregate)


def _load_bench(path: str) -> dict | None:
    try:
        with open(path) as f:
            for line in f.read().splitlines()[::-1]:
                if line.startswith("{"):
                    try:
                        return json.loads(line)
                    except json.JSONDecodeError:
                        continue  # mid-write/truncated line: keep scanning
    except OSError:
        return None
    return None


def _matrix_rows(path: str) -> dict:
    rows: dict = {}
    try:
        with open(path) as f:
            for line in f:
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "matrix" in obj:
                    return obj["matrix"]
                for k, v in obj.items():
                    if isinstance(v, dict):
                        rows[k] = v
    except OSError:
        pass
    return rows


def main() -> None:
    if len(sys.argv) > 1:
        cdir = sys.argv[1]
    else:
        caps = sorted(p for p in glob.glob(
            os.path.join(REPO, "bench_results", "capture_*"))
            if os.path.isdir(p))  # skip the capture_done marker file
        if not caps:
            print("no capture yet (bench_results/capture_*) — chip never "
                  "answered; see bench_results/probe_log.jsonl")
            return
        cdir = caps[-1]
    print(f"capture: {cdir}\n")

    marker = os.path.join(cdir, "INVALID")
    if os.path.exists(marker):
        with open(marker) as f:
            print("*** " + f.readline().strip() + " ***\n")

    bench = _load_bench(os.path.join(cdir, "BENCH_live.json"))
    if bench:
        if bench.get("fallback"):
            print("*** FALLBACK emission: chip was down at bench time; "
                  f"numbers come from {bench['fallback'].get('source')} ***")
        pc = bench.get("promoted_config")
        if pc and pc.get("error"):
            print(f"*** promotion file FAILED to apply ({pc['error'][:120]}) "
                  f"— headline below measured under plain auto ***")
        elif pc:
            ev = pc.get("evidence") or {}
            print(f"PROMOTED serving config: {pc.get('combo')} "
                  f"(decode {ev.get('decode_tok_per_s')} vs auto "
                  f"{ev.get('auto_decode_tok_per_s')} = {ev.get('gain')}x, "
                  f"from {ev.get('source')}) — headline below measured "
                  f"under it; BENCH_auto.json holds the auto twin")
        print(f"headline: {bench.get('metric')} = {bench.get('value')} "
              f"{bench.get('unit')}  (vs north star {NORTH_STAR:.0f}: "
              f"{100 * float(bench.get('value') or 0) / NORTH_STAR:.1f}%)")
        roof = bench.get("roofline_decode_tok_per_s")
        if roof:
            ratio = 100 * float(bench.get("value") or 0) / roof
            print(f"roofline (1-chip HBM): {roof} tok/s -> measured/roofline "
                  f"= {ratio:.1f}%")
            if ratio > 150:
                print("*** measured above the physical HBM roofline: these "
                      "are enqueue rates, not execution rates — the capture "
                      "pre-dates the fetch-forced timing fix ***")
        print(f"prefill MFU: {bench.get('prefill_mfu')}  "
              f"HBM util (decode): {bench.get('hbm_util_decode')}")
        for name, st in (bench.get("stages") or {}).items():
            keys = ("quant_mode", "decode_tok_per_s", "prefill_tok_per_s",
                    "sampled_decode_tok_per_s", "chunked_decode_tok_per_s",
                    "verify_k4_over_decode", "hbm_need_gb", "phase", "error")
            cells = "  ".join(f"{k}={st[k]}" for k in keys if k in st)
            print(f"  stage {name}: {cells}")
    else:
        print("no BENCH_live.json in capture")

    for preset in ("1b", "8b"):
        rows = _matrix_rows(os.path.join(cdir, f"matrix_{preset}.log"))
        if not rows:
            continue
        print(f"\nperf matrix ({preset}):")
        print(f"  {'combo':14s} {'decode':>10s} {'prefill':>10s}")
        for label, res in rows.items():
            print(f"  {label:14s} {str(res.get('decode_tok_per_s', '-')):>10s}"
                  f" {str(res.get('prefill_tok_per_s', '-')):>10s}"
                  + (f"   ({res['error'][:40]})" if res.get("error") else ""))

    promo = _load_bench(os.path.join(cdir, "promotion.json"))
    if promo is not None:
        print(f"\npromotion decision: {json.dumps(promo)[:400]}")

    tpu_log = os.path.join(cdir, "pytest_tpu.log")
    if os.path.exists(tpu_log):
        with open(tpu_log) as f:
            body = f.read()
        tail = body.splitlines()[-3:]
        print("\ntpu tier: " + " / ".join(tail))
        if "macbeth" in body:
            # `pytest -q` only prints test NAMES on failure: the substring
            # appearing means the 2049-step determinism chain (VERDICT r4
            # next #8) FAILED or errored on chip — surface it loudly
            print("  *** macbeth-on-chip appears in the log: the transcript "
                  "chain failed/errored — see pytest_tpu.log ***")

    for preset in ("8b", "1b"):
        plog = os.path.join(cdir, f"profile_{preset}.log")
        if os.path.exists(plog):
            with open(plog) as f:
                # profile_decode's own summary lines, incl. the RECONCILE
                # line that settles the 1.7x profiler-vs-chain systematic
                head = [ln for ln in f.read().splitlines()
                        if ln.startswith(("wall for", "lanes (", "RECONCILE"))][:3]
            print(f"profile {preset}: " + " | ".join(head))

    # reference context: its best published number is Llama 2 7B at
    # 296.69 ms/token INFERENCE on 8x Raspberry Pi 4B (report.pdf Fig. 3;
    # BASELINE.md) = 3.4 tok/s aggregate. The 1000 tok/s/chip north star is
    # a v5e-8 AGGREGATE target; one chip's HBM roofline for 8B Q40 is
    # ~97 tok/s (bench extras), so single-chip results print both ratios.
    if bench and bench.get("value"):
        v = float(bench["value"])
        if "8b" in str(bench.get("metric", "")):
            print(f"\nvs reference's own best published decode (7B-class, "
                  f"8 devices, 296.69 ms/tok = 3.4 tok/s): {v / 3.37:.1f}x")


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # e.g. `analyze_capture.py | head`
        # point stdout at devnull so interpreter-shutdown flush of the
        # broken pipe can't re-raise and dirty the exit status
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
