#!/bin/bash
# Chip watcher: probe the axon TPU every PROBE_INTERVAL seconds; in the first
# healthy window, automatically run the full perf capture sequence
# (bench.py -> perf_matrix 8b -> perf_matrix 1b -> promotion re-bench ->
# tpu-tier pytest -> f8 twin -> profiles) and save
# everything under bench_results/.  Designed to survive a wedged chip: every
# probe and every capture stage is a killable subprocess with a hard timeout.
#
# State files (all under bench_results/):
#   probe_log.jsonl   one line per probe: {"ts", "healthy", "latency_s"}
#   capture_done      marker: a full capture has been banked this session
#   RERUN             touch this file to request a fresh capture on the next
#                     healthy probe even if capture_done exists
#   capture_<ts>/     per-capture artifacts (bench JSON, pytest log, matrices)
set -u
REPO=/root/repo
OUT=$REPO/bench_results
mkdir -p "$OUT"
PROBE_INTERVAL=${PROBE_INTERVAL:-240}
PROBE_TIMEOUT=${PROBE_TIMEOUT:-30}

probe() {
    # healthy iff jax.devices() answers fast in a subprocess
    local t0 t1 rc
    t0=$(date +%s.%N)
    timeout "$PROBE_TIMEOUT" python -c "
import jax
ds = jax.devices()
assert ds, 'no devices'
print(ds[0].platform, ds[0].device_kind)
" >"$OUT/last_probe.out" 2>"$OUT/last_probe.err"
    rc=$?
    t1=$(date +%s.%N)
    local dt
    dt=$(python -c "print(f'{$t1-$t0:.2f}')")
    local healthy=false
    [ $rc -eq 0 ] && healthy=true
    echo "{\"ts\": \"$(date -u +%FT%TZ)\", \"healthy\": $healthy, \"rc\": $rc, \"latency_s\": $dt}" >> "$OUT/probe_log.jsonl"
    [ $rc -eq 0 ]
}

mirror() {
    # copy whatever artifacts exist so far into the TRACKED mirror dir —
    # called after EVERY stage, not just at capture end: a window that
    # truncates mid-capture (session end, wedge) must still hand the
    # completed stages to the end-of-round auto-commit
    local cdir=$1 adir=$2
    mkdir -p "$adir"
    local f
    for f in BENCH_live.json BENCH_auto.json BENCH_promoted.json \
             promotion.json status pytest_tpu.log matrix_1b.log \
             matrix_8b.log profile_8b.log profile_1b.log bench.stderr \
             s8k_f8.json INVALID; do
        [ -f "$cdir/$f" ] && cp "$cdir/$f" "$adir/" 2>/dev/null
    done
}

capture() {
    local ts cdir adir
    ts=$(date -u +%Y%m%dT%H%M%SZ)
    cdir=$OUT/capture_$ts
    adir=$REPO/capture_artifacts/$ts
    mkdir -p "$cdir"
    echo "capture start $ts" >> "$OUT/probe_log.jsonl.notes"
    cd "$REPO" || return 1

    # Round-5 priority order: the most decision-relevant artifacts bank
    # FIRST in case the chip wedges mid-window (the round-4 failure mode).

    # each capture derives its own promotion from its own matrices — a
    # stale winner from a previous capture must not leak into step 1's
    # production-config measurement (or get compared against itself)
    rm -f "$REPO/bench_promoted.json"

    # 1. bench.py, production config — wedge-proof by construction (parent
    #    never imports jax). Round-5 hardening means this now carries an
    #    honest prefill number and clean chunked/verify numbers.
    timeout 3600 python bench.py > "$cdir/BENCH_live.json" 2> "$cdir/bench.stderr"
    echo "bench rc=$?" >> "$cdir/status"
    mirror "$cdir" "$adir"

    # 2+3. kernel-choice sweeps — the turbo/scan-unroll A/B the round's
    #    perf verdict rides on. 8b FIRST: it is the headline shape and the
    #    combos are in decision-value order, so even a window truncated
    #    minutes after it opens banks the auto-vs-turbo verdict (step 1's
    #    bench already banked both presets' production decode numbers).
    timeout 4800 python tools/perf_matrix.py 8b 420 > "$cdir/matrix_8b.log" 2>&1
    echo "matrix_8b rc=$?" >> "$cdir/status"
    mirror "$cdir" "$adir"
    timeout 3600 python tools/perf_matrix.py 1b 300 > "$cdir/matrix_1b.log" 2>&1
    echo "matrix_1b rc=$?" >> "$cdir/status"
    mirror "$cdir" "$adir"

    # 4. promote the winning combo (>=10% over auto writes
    #    bench_promoted.json, which bench.py applies with provenance) and
    #    re-measure under it; the promoted line replaces BENCH_live.json
    #    so the round headline reflects the promoted serving config
    timeout 120 python tools/promote_config.py \
        "$cdir/matrix_8b.log" "$cdir/matrix_1b.log" \
        > "$cdir/promotion.json" 2> "$cdir/promotion.stderr"
    echo "promote rc=$?" >> "$cdir/status"
    if [ -f "$REPO/bench_promoted.json" ]; then
        timeout 2400 python bench.py > "$cdir/BENCH_promoted.json" \
            2> "$cdir/bench_promoted.stderr"
        echo "bench_promoted rc=$?" >> "$cdir/status"
        # only a LIVE measurement taken under the promoted config may
        # replace the headline — a fallback emission (chip wedged between
        # the matrices and this re-bench) would re-bank the auto capture
        # under a promoted label
        if python -c "
import json,sys
d=json.load(open('$cdir/BENCH_promoted.json'))
pc = d.get('promoted_config') or {}
# combo + no error = the promotion record loaded and governed this run
# (applied_env alone would reject a run whose knobs were already exported)
ok = (d.get('value') and not d.get('fallback')
      and pc.get('combo') and not pc.get('error'))
sys.exit(0 if ok else 1)" 2>/dev/null; then
            cp "$cdir/BENCH_live.json" "$cdir/BENCH_auto.json"
            cp "$cdir/BENCH_promoted.json" "$cdir/BENCH_live.json"
        fi
        mirror "$cdir" "$adir"
    fi

    # 5. TPU hardware test tier (incl. the 2049-step macbeth chain on chip)
    timeout 1800 flock -w 600 /tmp/dllama-chip.lock \
        env DLLAMA_TESTS_TPU=1 python -m pytest tests -m tpu -q \
        > "$cdir/pytest_tpu.log" 2>&1
    echo "pytest_tpu rc=$?" >> "$cdir/status"
    mirror "$cdir" "$adir"

    # 6. the f8-KV long-context comparison: the bench's default stages
    #    already measure 1b@s8k with a bf16 cache; this is the f8 twin
    #    (NO_PROMO: the knob isolation must not inherit a promoted mode)
    timeout 1200 env DLLAMA_BENCH_PRESET=1b@s8k DLLAMA_BENCH_KV=f8 \
        DLLAMA_BENCH_NO_PROMO=1 \
        python bench.py > "$cdir/s8k_f8.json" 2> "$cdir/s8k_f8.stderr"
    echo "s8k_f8 rc=$?" >> "$cdir/status"
    mirror "$cdir" "$adir"

    # 7+8. where the milliseconds go: per-op decode profiles (both presets;
    #    profile_decode prints the per-op-sum vs chain-time reconciliation)
    timeout 1200 flock -w 600 /tmp/dllama-chip.lock \
        python tools/profile_decode.py 8b 4 > "$cdir/profile_8b.log" 2>&1
    echo "profile_8b rc=$?" >> "$cdir/status"
    timeout 900 flock -w 450 /tmp/dllama-chip.lock \
        python tools/profile_decode.py 1b 4 > "$cdir/profile_1b.log" 2>&1
    echo "profile_1b rc=$?" >> "$cdir/status"

    touch "$OUT/capture_done"
    rm -f "$OUT/RERUN"
    echo "capture end $(date -u +%FT%TZ)" >> "$OUT/probe_log.jsonl.notes"

    # final mirror + human-readable summary into the TRACKED dir
    mirror "$cdir" "$adir"
    python "$REPO/tools/analyze_capture.py" "$cdir" \
        > "$adir/ANALYSIS.txt" 2>&1 || true
}

echo "watcher start $(date -u +%FT%TZ) interval=${PROBE_INTERVAL}s" >> "$OUT/probe_log.jsonl.notes"
while true; do
    if probe; then
        if [ ! -f "$OUT/capture_done" ] || [ -f "$OUT/RERUN" ]; then
            capture
        fi
    fi
    sleep "$PROBE_INTERVAL"
done
