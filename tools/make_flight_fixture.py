#!/usr/bin/env python
"""Regenerate ``tests/goldens/flight_dump.json`` — the golden
flight-recorder dump behind the Chrome-trace fixture test.

The fixture is a deterministic mini-run recorded through the REAL
:class:`runtime.flightrec.FlightRecorder` API (injected fake clock, no
jax): three requests stream through two slots with admissions, an
interleaved prefill, a budget preemption, retirements for three
different reasons, and paged block-pool occupancy on every tick. The
span ring entries are derived from the recorded event timeline, so
spans and ticks share one clock — exactly what a live dump looks like.

Run from the repo root::

    python tools/make_flight_fixture.py

and commit the regenerated golden together with whatever recorder
change made it necessary (tests/test_flightrec.py validates the
conversion, not byte equality, so regeneration is rarely needed).
"""

from __future__ import annotations

import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dllama_tpu.runtime import flightrec  # noqa: E402

OUT = REPO / "tests" / "goldens" / "flight_dump.json"

_T0 = 1_000_000_000  # ns
_STEP = 250_000      # 0.25 ms per clock read — every timestamp distinct


class _Clock:
    def __init__(self):
        self.t = _T0

    def __call__(self) -> int:
        self.t += _STEP
        return self.t


def record() -> dict:
    clk = _Clock()
    rec = flightrec.FlightRecorder(clock=clk)
    blocks = {"total": 30, "used": 0, "shared": 0, "reserved": 0}

    def tick(body, slots, used, shared):
        rec.begin_tick(queue_depth=body.pop("queue_depth", 0),
                       n_admissions=body.pop("n_admissions", 0))
        body["run"]()
        blocks.update(used=used, shared=shared)
        rec.end_tick(blocks=dict(blocks), slots=slots, prefill_budget=256)

    for rid, n_prompt in ((0, 24), (1, 9), (2, 17)):
        rec.note("submit", rid, n_prompt=n_prompt, max_tokens=8)

    def t1():
        rec.note("admit", 0, slot=0, reused=0, n_prompt=24)
        rec.note("admit", 1, slot=1, reused=0, n_prompt=9)
        rec.note_prefill(0, 2.0, 23)
        rec.note_prefill(1, 0.9, 8)
        rec.note("decode_armed", 1, slot=1, pos=8, reused=0)

    tick({"queue_depth": 3, "n_admissions": 0, "run": t1},
         [None, None], 4, 0)

    def t2():
        rec.note("preempt", 0, reason="prefill_budget")
        rec.note("decode_armed", 0, slot=0, pos=23, reused=0)
        rec.note_dispatch(1.5, 2, 2)
        rec.note("first_token", 0, slot=0)
        rec.note("first_token", 1, slot=1)

    tick({"queue_depth": 1, "n_admissions": 1, "run": t2}, [0, 1], 4, 0)

    def t3():
        rec.note_dispatch(1.4, 2, 2)
        rec.note("retire", 1, reason="eos", slot=1, n_tokens=3)
        rec.note("admit", 2, slot=1, reused=8, n_prompt=17)
        rec.note_prefill(2, 0.8, 8)
        rec.note("decode_armed", 2, slot=1, pos=16, reused=8)

    tick({"queue_depth": 1, "n_admissions": 0, "run": t3}, [0, None], 5, 1)

    def t4():
        rec.note_dispatch(1.6, 2, 2)
        rec.note("first_token", 2, slot=1)
        rec.note("retire", 0, reason="max_tokens", slot=0, n_tokens=8)

    tick({"queue_depth": 0, "n_admissions": 0, "run": t4}, [None, 2], 5, 1)

    def t5():
        rec.note_dispatch(1.3, 1, 1)
        rec.note("retire", 2, reason="max_tokens", slot=1, n_tokens=8)

    tick({"queue_depth": 0, "n_admissions": 0, "run": t5}, [None, None], 2, 0)

    # span ring entries derived from the recorded event timeline, so the
    # trace's request tracks line up with the scheduler tick track
    events = rec.snapshot()["events"]

    def at(rid, event):
        return next(e for e in events
                    if e["rid"] == rid and e["event"] == event)

    spans = []
    for rid in (0, 1, 2):
        sub = at(rid, "submit")["t_ns"]
        adm = at(rid, "admit")
        armed = at(rid, "decode_armed")["t_ns"]
        ret = at(rid, "retire")
        slot = adm["slot"]
        spans.append({"request_id": rid, "phase": "queue",
                      "start_ns": sub, "end_ns": adm["t_ns"],
                      "slot": slot, "n_tokens": 0})
        spans.append({"request_id": rid, "phase": "admit",
                      "start_ns": adm["t_ns"] - 100_000,
                      "end_ns": adm["t_ns"], "slot": slot,
                      "n_tokens": adm["reused"]})
        spans.append({"request_id": rid, "phase": "prefill_chunk",
                      "start_ns": adm["t_ns"],
                      "end_ns": adm["t_ns"] + 150_000, "slot": slot,
                      "n_tokens": adm["n_prompt"] - 1 - adm["reused"]})
        spans.append({"request_id": rid, "phase": "prefill",
                      "start_ns": adm["t_ns"], "end_ns": armed,
                      "slot": slot,
                      "n_tokens": adm["n_prompt"] - 1 - adm["reused"]})
        spans.append({"request_id": rid, "phase": "decode",
                      "start_ns": armed, "end_ns": ret["t_ns"],
                      "slot": slot, "n_tokens": ret["n_tokens"]})
    spans.sort(key=lambda s: (s["start_ns"], s["end_ns"]))

    doc = rec.payload("fixture", victims=[],
                      info={"generator": "tools/make_flight_fixture.py"},
                      spans=spans, requests=[])
    doc["pid"] = 0  # byte-stable regeneration
    return doc


def main() -> int:
    doc = record()
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n",
                   encoding="utf-8")
    print(f"✅ wrote {OUT} ({len(doc['ticks'])} ticks, "
          f"{len(doc['events'])} events, {len(doc['spans'])} spans)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
